# Decoder-only transformer LM (Llama-family architecture): the framework's
# flagship model, replacing the reference's external-process LLM element
# (reference: src/aiko_services/examples/llm/elements_llm.py:137-179, which
# shells out to Ollama/OpenAI -- no in-framework model exists).
#
# TPU-first design:
#   - params are a plain pytree; layers are STACKED on a leading axis and
#     executed with lax.scan (one compiled layer body, not n_layers copies);
#   - attention runs the Pallas flash kernel for prefill and a masked-cache
#     einsum for incremental decode; KV cache is a preallocated jax.Array
#     updated in place via dynamic_update_slice (donated across steps);
#   - param_specs() gives megatron-style TP over the "model" mesh axis +
#     FSDP over "fsdp"; activation constraints shard batch on "data" and
#     sequence on "seq";
#   - make_train_step() returns a jit-able (params, opt, batch) -> step
#     with f32 cross-entropy and optax updates, shardable over the mesh.

from __future__ import annotations

import math

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..parallel.attention import (
    flash_attention, ring_attention, sp_decode_attention,
    ulysses_attention)
from .layers import (
    apply_rotary, dense, init_dense, init_norm, repeat_kv, rms_norm,
    rotary_embedding)

__all__ = [
    "TransformerConfig", "init_params", "param_specs", "forward",
    "init_cache", "cache_specs", "decode_step", "generate",
    "generate_stream", "make_train_step", "count_params",
    "quantize_weights_int8", "quantized_param_specs",
    "init_paged_pool", "paged_prefill", "paged_decode_step",
    "paged_prefill_chunk", "paged_verify_step",
    "REMAT_POLICIES", "resolve_remat_policy",
]


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 8
    n_heads: int = 8
    n_kv_heads: int = 4
    d_ff: int = 1536
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    norm_eps: float = 1e-6
    dtype: str = "bfloat16"
    # True: the long-context path.  Prefill attention shards over the
    # mesh "seq" axis (mechanism below) and cached DECODE runs
    # sp_decode_attention with the cache length sharded over "seq" --
    # lay the cache out with cache_specs(sequence_parallel=True).
    # Requires an ambient jax.set_mesh holding a "seq" axis that divides
    # the sequence length (prefill) and cache length (decode); cached
    # prefill assumes pos=0.
    sequence_parallel: bool = False
    # "ring": KV shards rotate via ppermute (any head count; causal hops
    # skipped).  "ulysses": all-to-all swaps seq-sharding for
    # head-sharding and runs dense flash locally -- fewer collectives
    # when n_heads is divisible by the seq axis.
    sp_mechanism: str = "ring"
    # > 0: the FFN becomes a switch (top-1) mixture of experts with this
    # many experts; expert weights shard over the mesh "expert" axis
    # (param_specs), giving expert parallelism.  0 = dense FFN.
    n_experts: int = 0
    # expert capacity = ceil(moe_capacity_factor * L / E) tokens per
    # batch row; overflow tokens fall through on the residual.  <= 0
    # selects the masked-dense oracle (every expert computes every
    # token -- E x the FLOPs; only for tests/tiny E).
    moe_capacity_factor: float = 1.25
    # weight of the Switch load-balancing aux loss in make_train_step
    moe_aux_weight: float = 0.01
    # short sequences (L < E, i.e. incremental decode): gather only the
    # selected expert's weights per token -- optimal when experts are
    # replicated (single chip / no EP).  Set False when expert weights
    # shard on the "expert" axis, where the dispatch einsum keeps weights
    # stationary and moves (tiny) tokens instead.
    moe_decode_gather: bool = True
    # "int8": KV cache stores 8-bit codes + a per-(head, position) f32
    # scale -- halves cache HBM (doubling feasible decode batch at fixed
    # memory) and halves the cache-read bandwidth that bounds decode.
    # "" keeps the compute dtype.  Quantization happens at cache WRITE
    # (one rounding per token ever); reads dequantize into the attention
    # einsum, which XLA fuses into the operand load.
    kv_dtype: str = ""

    def __post_init__(self):
        if self.sp_mechanism not in ("ring", "ulysses"):
            raise ValueError(
                f"sp_mechanism must be 'ring' or 'ulysses', got "
                f"{self.sp_mechanism!r}")
        if self.kv_dtype not in ("", "int8"):
            raise ValueError(
                f"kv_dtype must be '' (compute dtype) or 'int8', got "
                f"{self.kv_dtype!r}")
        if self.kv_dtype == "int8" and self.sequence_parallel:
            raise ValueError(
                "kv_dtype='int8' is not supported on the "
                "sequence-parallel decode path (sp_decode_attention "
                "reads the raw cache shards)")

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @property
    def jnp_dtype(self):
        return jnp.dtype(self.dtype)


# -- parameters -------------------------------------------------------------

def _init_layer(key, config: TransformerConfig) -> dict:
    keys = jax.random.split(key, 8)
    d, hd, ff = config.d_model, config.head_dim, config.d_ff
    dtype = config.jnp_dtype
    layer = {
        "attn_norm": init_norm(d, dtype),
        "wq": init_dense(keys[0], d, config.n_heads * hd, dtype),
        "wk": init_dense(keys[1], d, config.n_kv_heads * hd, dtype),
        "wv": init_dense(keys[2], d, config.n_kv_heads * hd, dtype),
        "wo": init_dense(keys[3], config.n_heads * hd, d, dtype),
        "mlp_norm": init_norm(d, dtype),
    }
    if config.n_experts > 0:
        experts = config.n_experts

        def expert_weights(key, rows, cols):
            return {"w": (jax.random.normal(
                key, (experts, rows, cols), jnp.float32)
                / jnp.sqrt(jnp.float32(rows))).astype(dtype)}

        layer["router"] = init_dense(keys[7], d, experts, dtype)
        layer["w_gate"] = expert_weights(keys[4], d, ff)
        layer["w_up"] = expert_weights(keys[5], d, ff)
        layer["w_down"] = expert_weights(keys[6], ff, d)
    else:
        layer["w_gate"] = init_dense(keys[4], d, ff, dtype)
        layer["w_up"] = init_dense(keys[5], d, ff, dtype)
        layer["w_down"] = init_dense(keys[6], ff, d, dtype)
    return layer


def init_params(config: TransformerConfig, key) -> dict:
    embed_key, *layer_keys = jax.random.split(key, config.n_layers + 1)
    layers = [_init_layer(k, config) for k in layer_keys]
    stacked = jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *layers)
    return {
        "embed": {"w": (jax.random.normal(
            embed_key, (config.vocab_size, config.d_model), jnp.float32)
            * 0.02).astype(config.jnp_dtype)},
        "layers": stacked,
        "norm_out": init_norm(config.d_model, config.jnp_dtype),
    }


def param_specs(config: TransformerConfig,
                lm_head: bool = False) -> dict:
    """Megatron TP on 'model' + FSDP on 'fsdp' (+ EP on 'expert' for MoE
    weights); stacked-layer leaves carry a leading None for the scan axis.
    (Scaling-book recipe: shard the big matmuls, replicate the norms.)
    lm_head=True adds the untied-output-head spec (checkpoint-loaded
    Llama-3-8B+ params carry one)."""
    layer = {
        "attn_norm": {"scale": P(None, None)},
        "wq": {"w": P(None, "fsdp", "model")},
        "wk": {"w": P(None, "fsdp", "model")},
        "wv": {"w": P(None, "fsdp", "model")},
        "wo": {"w": P(None, "model", "fsdp")},
        "mlp_norm": {"scale": P(None, None)},
    }
    if config.n_experts > 0:
        layer["router"] = {"w": P(None, None, None)}
        layer["w_gate"] = {"w": P(None, "expert", "fsdp", "model")}
        layer["w_up"] = {"w": P(None, "expert", "fsdp", "model")}
        layer["w_down"] = {"w": P(None, "expert", "model", "fsdp")}
    else:
        layer["w_gate"] = {"w": P(None, "fsdp", "model")}
        layer["w_up"] = {"w": P(None, "fsdp", "model")}
        layer["w_down"] = {"w": P(None, "model", "fsdp")}
    specs = {
        "embed": {"w": P(None, "fsdp")},
        "layers": layer,
        "norm_out": {"scale": P(None)},
    }
    if lm_head:
        specs["lm_head"] = {"w": P(None, "fsdp")}
    return specs


def count_params(params) -> int:
    return sum(leaf.size for leaf in jax.tree_util.tree_leaves(params))


# -- weight-only int8 (serving decode) ---------------------------------------

_DENSE_QUANT_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def quantize_weights_int8(params: dict,
                          config: TransformerConfig) -> dict:
    """Weight-only int8 for SERVING: dense weights become 8-bit codes +
    a per-output-channel f32 scale (kept at the weight's rank so specs
    derive mechanically); embed / lm_head quantize per vocab ROW (one
    scale serves both the gather and the logits matmul, where the
    per-row scale factors out of the contraction).  Small-batch decode
    is weight-streaming-bound, so halving the bytes read per step is
    ~2x decode throughput at fixed batch.  Norms and biases stay f32;
    MoE expert FFNs stay unquantized (their dispatch einsums bypass
    dense()).  NOT for training -- optax rejects int8 leaves loudly."""
    def quant(entry: dict, axis: int) -> dict:
        w = entry["w"].astype(jnp.float32)
        scale = jnp.maximum(
            jnp.max(jnp.abs(w), axis=axis, keepdims=True), 1e-12) / 127.0
        codes = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
        out = {"w": codes, "w_scale": scale}
        if "b" in entry:
            out["b"] = entry["b"]
        return out

    dense_keys = (_DENSE_QUANT_KEYS[:4] if config.n_experts > 0
                  else _DENSE_QUANT_KEYS)
    layers = dict(params["layers"])
    for key in dense_keys:
        layers[key] = quant(layers[key], axis=-2)
    quantized = dict(params)
    quantized["layers"] = layers
    quantized["embed"] = quant(params["embed"], axis=-1)
    if "lm_head" in params:
        quantized["lm_head"] = quant(params["lm_head"], axis=-1)
    return quantized


def quantized_param_specs(config: TransformerConfig,
                          lm_head: bool = False) -> dict:
    """param_specs + a spec per w_scale plane: same layout as its
    weight with the quantization axis (collapsed to 1 by keepdims)
    unsharded -- -2 for dense per-output-channel scales, -1 for the
    embed/lm_head per-row scales."""
    def scale_spec(spec: P, axis: int) -> P:
        entries = list(tuple(spec))
        entries[axis] = None
        return P(*entries)

    specs = param_specs(config, lm_head=lm_head)
    dense_keys = (_DENSE_QUANT_KEYS[:4] if config.n_experts > 0
                  else _DENSE_QUANT_KEYS)
    layer = dict(specs["layers"])
    for key in dense_keys:
        layer[key] = dict(layer[key])
        layer[key]["w_scale"] = scale_spec(layer[key]["w"], -2)
    specs["layers"] = layer
    for name in ("embed", "lm_head"):
        if name in specs:
            specs[name] = dict(specs[name])
            specs[name]["w_scale"] = scale_spec(specs[name]["w"], -1)
    return specs


# -- KV cache ---------------------------------------------------------------

def init_cache(config: TransformerConfig, batch: int,
               max_len: int | None = None) -> dict:
    max_len = max_len or config.max_seq_len
    shape = (config.n_layers, batch, config.n_kv_heads, max_len,
             config.head_dim)
    if config.kv_dtype == "int8":
        scale_shape = shape[:-1] + (1,)
        return {"k": jnp.zeros(shape, jnp.int8),
                "k_scale": jnp.zeros(scale_shape, jnp.float32),
                "v": jnp.zeros(shape, jnp.int8),
                "v_scale": jnp.zeros(scale_shape, jnp.float32)}
    return {"k": jnp.zeros(shape, config.jnp_dtype),
            "v": jnp.zeros(shape, config.jnp_dtype)}


def cache_specs(sequence_parallel: bool = False,
                quantized: bool = False) -> dict:
    """Cache layout (layers, batch, kv_heads, len, head_dim): batch on
    "data", heads on "model" (TP); with sequence_parallel the cache LENGTH
    also shards over "seq", so long-context decode spreads KV bandwidth
    across the mesh (sp_decode_attention).  quantized=True adds the int8
    cache's per-position scale planes (same layout, head_dim collapsed)."""
    seq = "seq" if sequence_parallel else None
    spec = P(None, "data", "model", seq, None)
    if quantized:
        return {"k": spec, "k_scale": spec, "v": spec, "v_scale": spec}
    return {"k": spec, "v": spec}


def _quantize_kv(x):
    """(B, H, L, D) float -> (int8 codes, f32 scale (B, H, L, 1)):
    symmetric per-(batch, head, position) absmax scaling over head_dim.
    One rounding per written token; dequantization is codes * scale."""
    as_f32 = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(as_f32), axis=-1, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    codes = jnp.clip(jnp.round(as_f32 / scale), -127, 127).astype(jnp.int8)
    return codes, scale


# -- forward ----------------------------------------------------------------

def _attention(config: TransformerConfig, layer, h, cos, sin,
               cache_k=None, cache_v=None, pos=None,
               cache_k_scale=None, cache_v_scale=None):
    """Returns (output, new_k, new_v, new_k_scale, new_v_scale) -- the
    scale entries are None unless the cache is int8-quantized.  Without
    a cache: flash-attention causal prefill.  With a cache: write new
    K/V at `pos` (quantizing when the cache is int8), masked attention
    over the whole cache buffer."""
    batch, length, _ = h.shape
    hd = config.head_dim
    q = dense(layer["wq"], h).reshape(
        batch, length, config.n_heads, hd).transpose(0, 2, 1, 3)
    k = dense(layer["wk"], h).reshape(
        batch, length, config.n_kv_heads, hd).transpose(0, 2, 1, 3)
    v = dense(layer["wv"], h).reshape(
        batch, length, config.n_kv_heads, hd).transpose(0, 2, 1, 3)
    q = apply_rotary(q, cos, sin)
    k = apply_rotary(k, cos, sin)
    repeats = config.n_heads // config.n_kv_heads

    def sp_prefill(q, k, v):
        if config.sp_mechanism == "ulysses":
            return ulysses_attention(q, k, v, mesh=None, causal=True)
        return ring_attention(q, k, v, causal=True)

    if cache_k is None:
        if config.sequence_parallel:
            out = sp_prefill(q, repeat_kv(k, repeats),
                             repeat_kv(v, repeats))
        else:
            out = flash_attention(q, repeat_kv(k, repeats),
                                  repeat_kv(v, repeats), causal=True)
    else:
        quantized = cache_k.dtype == jnp.int8
        if quantized:
            k, k_scale = _quantize_kv(k)
            v, v_scale = _quantize_kv(v)
            cache_k_scale = jax.lax.dynamic_update_slice(
                cache_k_scale, k_scale, (0, 0, pos, 0))
            cache_v_scale = jax.lax.dynamic_update_slice(
                cache_v_scale, v_scale, (0, 0, pos, 0))
        cache_k = jax.lax.dynamic_update_slice(cache_k, k, (0, 0, pos, 0))
        cache_v = jax.lax.dynamic_update_slice(cache_v, v, (0, 0, pos, 0))
        if config.sequence_parallel and length > 1:
            # cached PREFILL: sequence-parallel attention over the fresh
            # K/V only -- valid solely at pos == 0 (the generate/prefill
            # contract); multi-token cached decode at pos > 0 would need
            # the earlier cache shards too.  Best-effort guard: a traced
            # pos cannot be checked at trace time, so the contract is
            # enforceable only for concrete ints
            if isinstance(pos, (int, np.integer)) and pos != 0:
                raise ValueError(
                    "sequence-parallel cached prefill requires pos == 0 "
                    f"(got pos={pos}); multi-token cached decode at "
                    "pos > 0 is not supported on this path")
            out = sp_prefill(q, repeat_kv(k, repeats),
                             repeat_kv(v, repeats))
        elif config.sequence_parallel:
            # long-context decode: cache length sharded over the mesh
            # "seq" axis; per-device attention touches only the local
            # cache shard (GQA heads expand inside the shard), partials
            # merge with a pmax/psum online-softmax
            out = sp_decode_attention(q, cache_k, cache_v, pos)
        else:
            if quantized:
                # dequantize into the einsum operand load (int8 codes x
                # per-position scale); the cache READ stays 8-bit, which
                # is the bandwidth that bounds decode
                k_eff = (cache_k.astype(jnp.float32)
                         * cache_k_scale).astype(q.dtype)
                v_eff = (cache_v.astype(jnp.float32)
                         * cache_v_scale).astype(q.dtype)
            else:
                k_eff, v_eff = cache_k, cache_v
            k_full = repeat_kv(k_eff, repeats)
            v_full = repeat_kv(v_eff, repeats)
            scale = 1.0 / jnp.sqrt(jnp.float32(hd))
            logits = jnp.einsum("bhqd,bhkd->bhqk", q, k_full,
                                preferred_element_type=jnp.float32) * scale
            max_len = cache_k.shape[2]
            q_pos = pos + jnp.arange(length)[:, None]
            k_pos = jnp.arange(max_len)[None, :]
            logits = jnp.where(k_pos <= q_pos, logits, -1e30)
            weights = jax.nn.softmax(logits, axis=-1)
            out = jnp.einsum("bhqk,bhkd->bhqd",
                             weights.astype(v_full.dtype), v_full)
    out = out.transpose(0, 2, 1, 3).reshape(batch, length, -1)
    return (dense(layer["wo"], out), cache_k, cache_v,
            cache_k_scale, cache_v_scale)


def _router(config: TransformerConfig, layer, x):
    """Top-1 router: returns (best (B,L) chosen expert ids, mask1 (B,L,E)
    its one-hot, weight (B,L,1) its router probability, aux scalar
    load-balancing loss).  aux = E * sum_e mean_tokens(mask1_e) *
    mean_tokens(prob_e) (Switch Transformer loss; minimized at uniform
    routing)."""
    router_logits = jnp.einsum(
        "bld,de->ble", x.astype(jnp.float32),
        layer["router"]["w"].astype(jnp.float32))
    router_probs = jax.nn.softmax(router_logits, axis=-1)
    best = jnp.argmax(router_probs, axis=-1)               # (B, L)
    mask1 = jax.nn.one_hot(best, config.n_experts,
                           dtype=jnp.float32)              # (B, L, E)
    weight = jnp.sum(router_probs * mask1, axis=-1,
                     keepdims=True)                        # (B, L, 1)
    fraction = jnp.mean(mask1, axis=(0, 1))                # (E,)
    prob_mass = jnp.mean(router_probs, axis=(0, 1))        # (E,)
    aux = config.n_experts * jnp.sum(fraction * prob_mass)
    return best, mask1, weight, aux


def _switch_moe_dense(config: TransformerConfig, layer, x):
    """Masked-dense switch dispatch: every expert computes every token, a
    one-hot mask selects the winner.  Exact (no capacity drops) but costs
    E x the dense FFN -- kept as the correctness oracle for the capacity
    dispatch and for tiny expert counts."""
    _, mask1, weight, aux = _router(config, layer, x)
    gate = jnp.einsum("bld,edf->blef", x, layer["w_gate"]["w"],
                      preferred_element_type=jnp.float32)
    up = jnp.einsum("bld,edf->blef", x, layer["w_up"]["w"],
                    preferred_element_type=jnp.float32)
    hidden = jax.nn.silu(gate) * up                        # (B, L, E, F)
    expert_out = jnp.einsum("blef,efd->bled", hidden,
                            layer["w_down"]["w"].astype(jnp.float32))
    mixed = jnp.sum(expert_out * mask1[..., None], axis=2)  # (B, L, D)
    return (mixed * weight).astype(x.dtype), aux


def _switch_moe(config: TransformerConfig, layer, x):
    """Switch (top-1) MoE FFN with CAPACITY-BASED dispatch.

    Each expert processes at most C = ceil(capacity_factor * L / E)
    tokens per batch row: tokens gather into a dense (B, E, C, D) buffer
    via a one-hot dispatch einsum (the TPU-friendly scatter -- static
    shapes, MXU-shaped matmuls, no dynamic indexing), the FFN runs
    batched over experts, and results scatter back weighted by the router
    probability.  Per-token FLOPs are ~capacity_factor x the dense FFN --
    independent of E.  Overflow tokens beyond an expert's capacity are
    dropped (standard Switch behavior; the residual connection carries
    them unchanged).  For short sequences (L < E, incremental decode)
    the capacity floor of one slot per expert would cost E x the FFN, so
    the path switches to per-token weight gather (moe_decode_gather).

    With expert weights and the (B, E, C, ...) buffers sharded on the
    "expert" mesh axis, each device computes only its local experts:
    per-device FLOPs scale with E_local, not E (true expert parallelism);
    XLA inserts the all-to-all-shaped collectives around the dispatch/
    combine einsums.

    Returns (output (B, L, D), aux load-balancing loss scalar).
    """
    if config.moe_capacity_factor <= 0:                    # oracle path
        return _switch_moe_dense(config, layer, x)
    batch, length, d_model = x.shape
    experts = config.n_experts
    if length < experts and config.moe_decode_gather:
        # capacity would floor at 1 slot x E experts (E x the FLOPs);
        # gather the chosen expert's weights per token instead
        return _switch_moe_gather(config, layer, x)
    capacity = max(1, math.ceil(
        config.moe_capacity_factor * length / experts))
    capacity = min(capacity, length)

    _, mask1, weight, aux = _router(config, layer, x)
    # position of each token within its expert's queue (per batch row)
    position = jnp.cumsum(mask1, axis=1) * mask1           # 1-based
    keep = mask1 * (position <= capacity)                  # (B, L, E)
    disp = keep[..., None] * jax.nn.one_hot(
        ((position - 1.0) * keep).astype(jnp.int32), capacity,
        dtype=jnp.float32)
    # disp: (B, L, E, C) one-hot dispatch/combine tensor.  Everything
    # stays in model dtype into the MXU matmuls (one-hot selection is
    # exact in bf16); accumulation is f32 via preferred_element_type.
    expert_in = jnp.einsum("bld,blec->becd", x, disp.astype(x.dtype))
    gate = jnp.einsum("becd,edf->becf", expert_in, layer["w_gate"]["w"],
                      preferred_element_type=jnp.float32)
    up = jnp.einsum("becd,edf->becf", expert_in, layer["w_up"]["w"],
                    preferred_element_type=jnp.float32)
    hidden = (jax.nn.silu(gate) * up).astype(x.dtype)      # (B, E, C, F)
    expert_out = jnp.einsum("becf,efd->becd", hidden,
                            layer["w_down"]["w"],
                            preferred_element_type=jnp.float32)
    combine = disp * weight[..., None]                     # (B, L, E, C)
    out = jnp.einsum("becd,blec->bld", expert_out, combine)
    return out.astype(x.dtype), aux


def _switch_moe_gather(config: TransformerConfig, layer, x):
    """Per-token expert-weight GATHER dispatch for short sequences
    (incremental decode, L < E): read only the selected expert's weight
    rows -- per-token FLOPs and HBM reads equal ONE dense FFN, vs the
    capacity path's E floor-of-one slots.  Optimal when expert weights
    are replicated (single chip); under EP sharding prefer the dispatch
    einsums (moe_decode_gather=False) so weights stay stationary."""
    best, _, weight, aux = _router(config, layer, x)
    wg = jnp.take(layer["w_gate"]["w"], best, axis=0)      # (B, L, D, F)
    wu = jnp.take(layer["w_up"]["w"], best, axis=0)
    wd = jnp.take(layer["w_down"]["w"], best, axis=0)      # (B, L, F, D)
    gate = jnp.einsum("bld,bldf->blf", x, wg,
                      preferred_element_type=jnp.float32)
    up = jnp.einsum("bld,bldf->blf", x, wu,
                    preferred_element_type=jnp.float32)
    hidden = (jax.nn.silu(gate) * up).astype(x.dtype)
    out = jnp.einsum("blf,blfd->bld", hidden, wd,
                     preferred_element_type=jnp.float32)
    return (out * weight).astype(x.dtype), aux


def _embed(params: dict, config: TransformerConfig, tokens):
    """Token embedding gather shared by forward() and the paged decode
    path (one definition, so the two can never drift bitwise).
    mode="clip": out-of-vocab ids clamp to the last row instead of
    jnp.take's default FILL mode, whose NaN embeddings silently poison
    every downstream activation."""
    h = jnp.take(params["embed"]["w"], tokens, axis=0, mode="clip")
    if h.dtype == jnp.int8:
        # int8 embed (quantize_weights_int8): gather the rows' scales
        # alongside and dequantize only the gathered tokens
        h = (h.astype(jnp.float32)
             * jnp.take(params["embed"]["w_scale"], tokens, axis=0,
                        mode="clip")).astype(config.jnp_dtype)
    return h


def _mlp_block(config: TransformerConfig, layer, mlp_in):
    """One layer's FFN (dense SwiGLU or switch MoE), shared by
    forward() and the paged decode path.  Returns (output, aux)."""
    if config.n_experts > 0:
        return _switch_moe(config, layer, mlp_in)
    return dense(
        layer["w_down"],
        jax.nn.silu(dense(layer["w_gate"], mlp_in))
        * dense(layer["w_up"], mlp_in)), jnp.zeros((), jnp.float32)


def _lm_head(params: dict, config: TransformerConfig, h):
    """Output norm + logits head shared by forward() and the paged
    decode path.  Untied output head when the checkpoint ships one
    (Llama-3-8B+, models/weights.py load_llama_params); tied embedding
    otherwise."""
    h = rms_norm(params["norm_out"], h, config.norm_eps)
    head = params.get("lm_head", params["embed"])
    logits = jnp.einsum("bld,vd->blv", h.astype(jnp.float32),
                        head["w"].astype(jnp.float32))
    if head["w"].dtype == jnp.int8:
        # per-row scales factor out of the contraction: the einsum
        # streams 8-bit codes, the (V,) scale applies to the result
        logits = logits * head["w_scale"][:, 0]
    return logits


def forward(params: dict, config: TransformerConfig, tokens,
            cache: dict | None = None, pos: int = 0,
            activation_specs: bool = False, return_aux: bool = False,
            remat_policy: str | None = None):
    """tokens (B, L) int32 -> logits (B, L, V) [+ updated cache].

    With cache=None this is a pure causal prefill (training / scoring).
    With a cache, K/V are written at `pos` (traced or static int) and the
    updated cache is returned -- the incremental-decode path.
    return_aux=True (cache-less path only) additionally returns the mean
    MoE load-balancing loss across layers (0.0 for dense FFN).
    remat_policy (cache-less path only) wraps the per-layer scan body in
    jax.checkpoint with the named jax.checkpoint_policies entry, trading
    backward-pass recompute for activation memory (REMAT_POLICIES).
    """
    if return_aux and cache is not None:
        raise ValueError(
            "return_aux is only meaningful on the cache-less (training/"
            "scoring) path; with a cache forward returns (logits, cache)")
    if remat_policy not in (None, "none") and cache is not None:
        raise ValueError(
            "remat_policy is only meaningful on the cache-less "
            "(training/scoring) path; incremental decode saves nothing "
            "by rematerializing")
    if activation_specs:
        # batch on "data", sequence on "seq" -- but only the axes the
        # ambient mesh actually has (an EP-only mesh has no "seq")
        names = jax.sharding.get_abstract_mesh().axis_names
        act_spec = P("data" if "data" in names else None,
                     "seq" if "seq" in names else None, None)
    h = _embed(params, config, tokens)
    if activation_specs:
        h = jax.lax.with_sharding_constraint(h, act_spec)
    positions = pos + jnp.arange(tokens.shape[1])
    cos, sin = rotary_embedding(positions, config.head_dim,
                                config.rope_theta)
    cos, sin = cos[None, None], sin[None, None]  # (1, 1, L, hd/2)

    def layer_step(carry, xs):
        h, aux_sum = carry
        layer, layer_cache = xs
        attn_out, new_k, new_v, new_k_scale, new_v_scale = _attention(
            config, layer, rms_norm(layer["attn_norm"], h, config.norm_eps),
            cos, sin,
            cache_k=None if layer_cache is None else layer_cache["k"],
            cache_v=None if layer_cache is None else layer_cache["v"],
            cache_k_scale=(None if layer_cache is None
                           else layer_cache.get("k_scale")),
            cache_v_scale=(None if layer_cache is None
                           else layer_cache.get("v_scale")),
            pos=pos)
        h = h + attn_out
        mlp_out, aux = _mlp_block(
            config, layer, rms_norm(layer["mlp_norm"], h, config.norm_eps))
        aux_sum = aux_sum + aux
        h = h + mlp_out
        if activation_specs:
            h = jax.lax.with_sharding_constraint(h, act_spec)
        if new_k is None:
            new_cache = None
        elif new_k_scale is not None:
            new_cache = {"k": new_k, "k_scale": new_k_scale,
                         "v": new_v, "v_scale": new_v_scale}
        else:
            new_cache = {"k": new_k, "v": new_v}
        return (h, aux_sum), new_cache

    aux0 = jnp.zeros((), jnp.float32)
    if cache is None:
        body = lambda carry, layer: layer_step(carry, (layer, None))  # noqa: E731
        policy = resolve_remat_policy(remat_policy)
        if policy is not None:
            # remat over the scanned layer body: the standard trade --
            # drop (policy-selected) activations in the forward pass,
            # recompute them during backward.  prevent_cse=False is the
            # documented setting under scan (the scan boundary already
            # blocks the CSE that prevent_cse guards against).
            body = jax.checkpoint(body, policy=policy, prevent_cse=False)
        (h, aux_sum), _ = jax.lax.scan(body, (h, aux0), params["layers"])
        new_cache = None
    else:
        (h, aux_sum), new_cache = jax.lax.scan(
            layer_step, (h, aux0), (params["layers"], cache))
    logits = _lm_head(params, config, h)
    if new_cache is None:
        if return_aux:
            return logits, aux_sum / max(config.n_layers, 1)
        return logits
    return logits, new_cache


# -- generation -------------------------------------------------------------

@partial(jax.jit, static_argnames=("config",), donate_argnums=(2,))
def decode_step(params, config: TransformerConfig, cache, token, pos):
    """One incremental decode step: token (B, 1) at absolute position pos
    (B-shaped traced int32).  Returns (next_token greedy, logits, cache)."""
    logits, cache = forward(params, config, token, cache=cache, pos=pos)
    next_token = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
    return next_token[:, None], logits, cache


@partial(jax.jit, static_argnames=("config", "max_new_tokens"),
         donate_argnums=(3,))
def _generate_compiled(params, config: TransformerConfig, prompt, cache,
                       max_new_tokens: int):
    """Module-level jit (stable function identity, so repeated generate()
    calls hit the compile cache): prefill + fori_loop greedy decode."""
    batch, prompt_len = prompt.shape
    logits, cache = forward(params, config, prompt, cache=cache, pos=0)
    first = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)[:, None]
    out = jnp.zeros((batch, max_new_tokens), jnp.int32)
    out = jax.lax.dynamic_update_slice(out, first, (0, 0))

    def body(step, carry):
        out, cache = carry
        token = jax.lax.dynamic_slice(out, (0, step - 1), (batch, 1))
        step_logits, cache = forward(params, config, token, cache=cache,
                                     pos=prompt_len + step - 1)
        next_token = jnp.argmax(step_logits[:, -1], axis=-1).astype(
            jnp.int32)[:, None]
        out = jax.lax.dynamic_update_slice(out, next_token, (0, step))
        return out, cache

    out, cache = jax.lax.fori_loop(1, max_new_tokens, body, (out, cache))
    return out, cache


def generate(params, config: TransformerConfig, prompt,
             max_new_tokens: int, cache=None):
    """Greedy generation: prefill the prompt, then fori_loop decode inside
    one jit.  Returns (tokens (B, max_new_tokens) int32, cache).  A
    caller-supplied cache (e.g. mesh-sharded) is DONATED to the jit; use
    the returned cache, never the invalidated input buffers."""
    batch, prompt_len = prompt.shape
    if cache is None:
        cache = init_cache(config, batch,
                           max_len=prompt_len + max_new_tokens)
    return _generate_compiled(params, config, prompt, cache,
                              int(max_new_tokens))


@partial(jax.jit, static_argnames=("config",), donate_argnums=(3,))
def _prefill_step(params, config: TransformerConfig, prompt, cache):
    logits, cache = forward(params, config, prompt, cache=cache, pos=0)
    first = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
    return first[:, None], cache


@partial(jax.jit, static_argnames=("config", "chunk"), donate_argnums=(3,))
def _decode_chunk(params, config: TransformerConfig, token, cache, pos,
                  chunk: int):
    """`chunk` greedy steps as ONE device program (lax.fori_loop): one
    dispatch per chunk, so host/tunnel latency never rides per-token."""
    batch = token.shape[0]
    out = jnp.zeros((batch, chunk), jnp.int32)

    def body(step, carry):
        out, token, cache = carry
        logits, cache = forward(params, config, token, cache=cache,
                                pos=pos + step)
        token = jnp.argmax(logits[:, -1], axis=-1).astype(
            jnp.int32)[:, None]
        out = jax.lax.dynamic_update_slice(out, token, (0, step))
        return out, token, cache

    out, token, cache = jax.lax.fori_loop(0, chunk, body,
                                          (out, token, cache))
    return out, token, cache


def generate_stream(params, config: TransformerConfig, prompt,
                    max_new_tokens: int, cache=None, chunk: int = 8):
    """Streaming greedy generation: yields (offset, tokens (B, n)) numpy
    chunks as they decode -- the serving path behind LMGenerate's streamed
    token output (reference capability: Ollama token streaming,
    elements_llm.py:137-179).  Prefill is one jit; decode runs in
    on-device chunks of `chunk` steps, so the host sees one dispatch +
    one transfer per chunk."""
    batch, prompt_len = prompt.shape
    if cache is None:
        cache = init_cache(config, batch,
                           max_len=prompt_len + max_new_tokens)
    token, cache = _prefill_step(params, config, prompt, cache)
    yield 0, jax.device_get(token)
    produced = 1
    while produced < max_new_tokens:
        size = min(chunk, max_new_tokens - produced)
        block, token, cache = _decode_chunk(
            params, config, token, cache,
            jnp.int32(prompt_len + produced - 1), int(size))
        yield produced, jax.device_get(block)
        produced += size


# -- paged KV: the continuous-batching decode substrate ----------------------
#
# The fori_loop generate() above is a CLOSED batch: every sequence in
# the jit must finish before any new request touches the chip.  The
# decode/ subsystem replaces the per-request cache with one fixed-size
# POOL of KV blocks plus per-slot block tables, so requests are
# admitted and evicted mid-decode without ever changing an array shape
# (the same zero-filler trick the micro-batch scheduler uses for group
# arity).  Three invariants make it bit-compatible with generate():
#
#   - block contents are written by the SAME forward()/_quantize_kv
#     math as the contiguous cache (prefill literally reshapes a
#     forward() cache into blocks);
#   - the decode step's attention is the SAME masked einsum as
#     _attention's cached branch, applied to the block-table gather --
#     positions beyond a slot's cursor hold garbage (stale or trash)
#     but are masked to exactly zero weight, like the zeros of a fresh
#     contiguous cache;
#   - inactive slots compute on a reserved TRASH block (index 0, never
#     allocated) so the step's shapes -- (slots, max_blocks) -- are
#     compile-time constants across any admission/eviction sequence.

def init_paged_pool(config: TransformerConfig, num_blocks: int,
                    block_size: int) -> dict:
    """Preallocated paged KV pool: `num_blocks` blocks of `block_size`
    token positions each, shared by every decode slot through per-slot
    block tables.  Block 0 is the engine's reserved trash block
    (inactive-slot writes land there).  Same leaf names/dtypes as
    init_cache, so the int8 KV path carries over unchanged."""
    shape = (config.n_layers, num_blocks, config.n_kv_heads, block_size,
             config.head_dim)
    if config.kv_dtype == "int8":
        scale_shape = shape[:-1] + (1,)
        return {"k": jnp.zeros(shape, jnp.int8),
                "k_scale": jnp.zeros(scale_shape, jnp.float32),
                "v": jnp.zeros(shape, jnp.int8),
                "v_scale": jnp.zeros(scale_shape, jnp.float32)}
    return {"k": jnp.zeros(shape, config.jnp_dtype),
            "v": jnp.zeros(shape, config.jnp_dtype)}


@partial(jax.jit, static_argnames=("config",), donate_argnums=(2,))
def paged_prefill(params, config: TransformerConfig, pool, prompt,
                  table_row, true_len):
    """Prefill one request into its pool blocks.  prompt is (1, Lb)
    with Lb a multiple of the pool's block size (the engine right-pads
    to a bucket, so one executable serves every prompt length in the
    bucket); table_row (max_blocks,) names the slot's blocks, of which
    the first Lb//block_size receive the prompt's K/V.  Returns
    (pool, first_token) where first_token is the greedy token after the
    TRUE prompt length -- causal masking makes logits at true_len-1
    independent of the right-padding.  One executable per bucket; the
    decode loop never recompiles (paged_decode_step below)."""
    block_size = pool["k"].shape[3]
    local = init_cache(config, 1, max_len=prompt.shape[1])
    logits, local = forward(params, config, prompt, cache=local, pos=0)
    first = jnp.argmax(logits[0, true_len - 1]).astype(jnp.int32)
    blocks = prompt.shape[1] // block_size
    new_pool = {}
    for name, written in local.items():
        # (nl, 1, H, Lb, d) -> (nl, blocks, H, block_size, d), scattered
        # into the slot's first `blocks` pool entries
        entry = written[:, 0]
        layers, heads, _, depth = entry.shape
        entry = entry.reshape(layers, heads, blocks, block_size,
                              depth).transpose(0, 2, 1, 3, 4)
        new_pool[name] = pool[name].at[:, table_row[:blocks]].set(entry)
    return new_pool, first


def _paged_window(params, config: TransformerConfig, pool, tables,
                  positions, tokens, write_blocks, write_offsets):
    """Shared paged-attention step over a per-slot TOKEN WINDOW -- the
    one traced implementation behind paged_decode_step (window 1),
    paged_verify_step (speculative verification, window k+1), and
    paged_prefill_chunk (chunked prefill, window = chunk bucket).

    tokens (slots, W) are consumed left-to-right per slot: window
    position i sits at absolute position positions[slot] + i, its K/V
    lands at (write_blocks[slot, i], write_offsets[slot, i]) -- writes
    happen for the WHOLE window before the attention gather, so later
    window positions attend to earlier ones causally, and rows the
    engine wants inert point their writes at the trash block.  Returns
    (pool, greedy (slots, W)) where greedy[s, i] is the greedy token
    AFTER consuming window positions 0..i -- exactly what W successive
    single-token decode steps would produce, which is the bit-identity
    contract the chunked-prefill and speculative tests pin."""
    block_size = pool["k"].shape[3]
    quantized = config.kv_dtype == "int8"
    h = _embed(params, config, tokens)
    slots, window = tokens.shape
    q_pos = positions[:, None] + jnp.arange(window)[None, :]  # (S, W)
    cos, sin = rotary_embedding(q_pos, config.head_dim,
                                config.rope_theta)
    cos, sin = cos[:, None], sin[:, None]        # (S, 1, W, hd/2)
    hd = config.head_dim
    repeats = config.n_heads // config.n_kv_heads

    def gather(pool_layer):
        # (num_blocks, H, bs, d)[tables] -> (S, MB, H, bs, d) -> the
        # slot's contiguous cache view (S, H, MB*bs, d)
        view = pool_layer[tables]
        s, max_blocks, heads, _, depth = view.shape
        return view.transpose(0, 2, 1, 3, 4).reshape(
            s, heads, max_blocks * block_size, depth)

    def layer_step(carry, xs):
        h = carry
        if quantized:
            layer, pool_k, k_scale, pool_v, v_scale = xs
        else:
            layer, pool_k, pool_v = xs
        x = rms_norm(layer["attn_norm"], h, config.norm_eps)
        q = dense(layer["wq"], x).reshape(
            slots, window, config.n_heads, hd).transpose(0, 2, 1, 3)
        k = dense(layer["wk"], x).reshape(
            slots, window, config.n_kv_heads, hd).transpose(0, 2, 1, 3)
        v = dense(layer["wv"], x).reshape(
            slots, window, config.n_kv_heads, hd).transpose(0, 2, 1, 3)
        q = apply_rotary(q, cos, sin)
        k = apply_rotary(k, cos, sin)
        if quantized:
            k, k_scale_new = _quantize_kv(k)
            v, v_scale_new = _quantize_kv(v)
            k_scale = k_scale.at[write_blocks, :, write_offsets, :].set(
                k_scale_new.transpose(0, 2, 1, 3))
            v_scale = v_scale.at[write_blocks, :, write_offsets, :].set(
                v_scale_new.transpose(0, 2, 1, 3))
        # (S, H, W, d) -> (S, W, H, d): advanced indexing with the
        # (S, W) block/offset pairs scatters every window position of
        # every slot in one update
        pool_k = pool_k.at[write_blocks, :, write_offsets, :].set(
            k.transpose(0, 2, 1, 3))
        pool_v = pool_v.at[write_blocks, :, write_offsets, :].set(
            v.transpose(0, 2, 1, 3))
        if quantized:
            # dequantize into the einsum operand load, exactly as the
            # contiguous int8 cache path does
            k_eff = (gather(pool_k).astype(jnp.float32)
                     * gather(k_scale)).astype(q.dtype)
            v_eff = (gather(pool_v).astype(jnp.float32)
                     * gather(v_scale)).astype(q.dtype)
        else:
            k_eff, v_eff = gather(pool_k), gather(pool_v)
        k_full = repeat_kv(k_eff, repeats)
        v_full = repeat_kv(v_eff, repeats)
        scale = 1.0 / jnp.sqrt(jnp.float32(hd))
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k_full,
                            preferred_element_type=jnp.float32) * scale
        k_pos = jnp.arange(k_full.shape[2])[None, None, None, :]
        logits = jnp.where(k_pos <= q_pos[:, None, :, None], logits,
                           -1e30)
        weights = jax.nn.softmax(logits, axis=-1)
        out = jnp.einsum("bhqk,bhkd->bhqd",
                         weights.astype(v_full.dtype), v_full)
        out = out.transpose(0, 2, 1, 3).reshape(slots, window, -1)
        h = h + dense(layer["wo"], out)
        mlp_out, _ = _mlp_block(
            config, layer, rms_norm(layer["mlp_norm"], h, config.norm_eps))
        h = h + mlp_out
        if quantized:
            return h, (pool_k, k_scale, pool_v, v_scale)
        return h, (pool_k, pool_v)

    if quantized:
        xs = (params["layers"], pool["k"], pool["k_scale"], pool["v"],
              pool["v_scale"])
    else:
        xs = (params["layers"], pool["k"], pool["v"])
    h, updated = jax.lax.scan(layer_step, h, xs)
    if quantized:
        new_pool = {"k": updated[0], "k_scale": updated[1],
                    "v": updated[2], "v_scale": updated[3]}
    else:
        new_pool = {"k": updated[0], "v": updated[1]}
    logits = _lm_head(params, config, h)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return new_pool, greedy


@partial(jax.jit, static_argnames=("config",), donate_argnums=(2,))
def paged_decode_step(params, config: TransformerConfig, pool, tables,
                      positions, tokens, write_blocks, write_offsets):
    """ONE greedy decode step over ALL slots of a continuous-batching
    engine.  tables (slots, max_blocks) int32 maps each slot's logical
    positions onto pool blocks; positions (slots,) is each slot's next
    write position; tokens (slots, 1) the previous greedy token;
    write_blocks/write_offsets (slots,) the precomputed pool location
    of this step's K/V (the engine points INACTIVE slots at the trash
    block, so the call is shape-stable across any admit/evict
    sequence -- zero recompiles after the first step).  Returns
    (pool, next_tokens (slots, 1)); inactive rows are garbage the
    engine ignores.

    Per-slot positions (unlike forward's scalar `pos`) are the whole
    point: slot 3 can be 400 tokens into its completion while slot 0 is
    on its first -- the rotary phase and causal mask resolve per row.
    The window-1 instantiation of _paged_window."""
    return _paged_window(params, config, pool, tables, positions,
                         tokens, write_blocks[:, None],
                         write_offsets[:, None])


@partial(jax.jit, static_argnames=("config",), donate_argnums=(2,))
def paged_verify_step(params, config: TransformerConfig, pool, tables,
                      positions, tokens, write_blocks, write_offsets):
    """Speculative-decoding verification: a decode step with a TOKEN
    WINDOW per slot instead of a single position.  tokens (slots, W)
    holds [last emitted token, draft_1..draft_{W-1}] per slot; the
    target consumes all W positions in ONE batched forward (the
    weight stream is read once for W tokens -- the whole point at
    small batch) and returns greedy (slots, W) where greedy[s, i] is
    the target's greedy token after window position i.  The engine
    accepts the longest prefix with draft_j == greedy[j-1], which
    keeps emitted tokens bit-identical to plain greedy decode.
    write_blocks/write_offsets (slots, W); overflow/inactive window
    positions point at the trash block.  One executable per W."""
    return _paged_window(params, config, pool, tables, positions,
                         tokens, write_blocks, write_offsets)


@partial(jax.jit, static_argnames=("config",), donate_argnums=(2,))
def paged_prefill_chunk(params, config: TransformerConfig, pool, tokens,
                        table_row, start, write_blocks, write_offsets):
    """Prefill ONE request's next `C` prompt tokens into its pool
    blocks, attending to the already-written KV blocks of earlier
    chunks through the block table -- the SARATHI-style chunked
    prefill that bounds per-call attention cost (C x written-so-far
    instead of L x L) so the engine can interleave prefill progress
    with decode steps.  tokens (1, C) is the chunk right-padded to its
    bucket; table_row (max_blocks,) the slot's block table; start the
    chunk's first absolute position; write_blocks/write_offsets (C,)
    the per-token pool locations (padded tail -> trash block).
    Returns (pool, greedy (C,)): greedy[i] is the greedy token after
    prompt position start + i, so the FINAL chunk's entry at the true
    prompt end is the request's first generated token, bit-identical
    to monolithic paged_prefill's.  One executable per power-of-two
    chunk bucket."""
    pool, greedy = _paged_window(
        params, config, pool, table_row[None],
        jnp.reshape(start, (1,)), tokens, write_blocks[None],
        write_offsets[None])
    return pool, greedy[0]


# -- training ---------------------------------------------------------------

# Named jax.checkpoint_policies entries the remat sweep accepts
# (make_train_step(remat_policy=), bench train `remat` knob).  "none"
# keeps today's behavior: no jax.checkpoint wrapper at all, XLA saves
# every scan residual.  The others trade backward-pass recompute for
# activation memory; every policy produces BIT-IDENTICAL losses (the
# recomputed ops are the same ops -- tested), so the sweep is purely a
# time/memory frontier.
REMAT_POLICIES = ("none", "everything_saveable", "nothing_saveable",
                  "dots_saveable", "dots_with_no_batch_dims_saveable")


def resolve_remat_policy(name: str | None):
    """Remat-policy name -> jax.checkpoint policy callable (None =
    don't wrap the layer body at all)."""
    if name is None or name == "none":
        return None
    if name not in REMAT_POLICIES:
        raise ValueError(
            f"unknown remat_policy {name!r}; choose from "
            f"{REMAT_POLICIES}")
    return getattr(jax.checkpoint_policies, name)


def make_train_step(config: TransformerConfig, optimizer,
                    sharded: bool = False,
                    remat_policy: str | None = None):
    """Returns train_step(params, opt_state, tokens) -> (params, opt_state,
    loss).  Next-token cross-entropy in f32; jit with donation.  With
    sharded=True, activation sharding constraints (data/seq) are inserted
    for mesh execution.  remat_policy names a REMAT_POLICIES entry
    applied to the per-layer scan body (ROADMAP #3b: the train-MFU
    recompute-share sweep)."""
    resolve_remat_policy(remat_policy)  # fail fast on typos

    def loss_fn(params, tokens):
        logits, aux = forward(params, config, tokens[:, :-1],
                              activation_specs=sharded, return_aux=True,
                              remat_policy=remat_policy)
        targets = tokens[:, 1:]
        log_probs = jax.nn.log_softmax(logits, axis=-1)
        taken = jnp.take_along_axis(
            log_probs, targets[..., None], axis=-1, mode="clip")[..., 0]
        return -jnp.mean(taken) + config.moe_aux_weight * aux

    @partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(
            lambda p, u: (p + u.astype(p.dtype)), params, updates)
        return params, opt_state, loss

    return train_step
