# Reference-scale model configurations.
#
# The reference's flagship workloads and their scales (BASELINE.md):
#   - Llama-3-8B chat (reference elements_llm.py:137-179 via Ollama)
#   - Whisper tiny..large speech-to-text ladder, 39M..1550M params
#     (reference speech_elements.py:186-192)
#   - YOLOv8 detection (reference yolo.py:51-87)
# These presets instantiate this framework's models at those shapes so the
# same capability runs in-framework, sharded over the mesh, with weights
# ingested through models/weights.py.

from __future__ import annotations

from .asr import AsrConfig
from .detector import DetectorConfig
from .transformer import TransformerConfig

__all__ = [
    "LLAMA3_8B", "LLAMA32_1B", "LM_TOY",
    "WHISPER_TINY", "WHISPER_SMALL",
    "YOLOV8N_SHAPE", "DETECTOR_TOY",
    "transformer_flops_per_token", "asr_flops_per_example",
    "tts_flops_per_example",
    "detector_flops_per_image",
]

# Llama-3-8B architecture (BASELINE config 4: v5e-4, streamed tokens)
LLAMA3_8B = TransformerConfig(
    vocab_size=128256, d_model=4096, n_layers=32, n_heads=32,
    n_kv_heads=8, d_ff=14336, max_seq_len=8192, rope_theta=500000.0,
    dtype="bfloat16")

# Llama-3.2-1B architecture: the largest Llama that decodes comfortably on
# one v5e chip alongside its KV cache (tied embeddings)
LLAMA32_1B = TransformerConfig(
    vocab_size=128256, d_model=2048, n_layers=16, n_heads=32,
    n_kv_heads=8, d_ff=8192, max_seq_len=8192, rope_theta=500000.0,
    dtype="bfloat16")

# small config for hermetic tests / CPU runs
LM_TOY = TransformerConfig(
    vocab_size=4096, d_model=256, n_layers=4, n_heads=8, n_kv_heads=4,
    d_ff=768, max_seq_len=512, dtype="float32")

# Whisper ladder shapes (reference speech_elements.py:186-192:
# tiny 39M 32x ... small 244M 6x); multilingual vocab 51865.  Special
# token ids keep the AsrConfig defaults (sot 1 / eot 2) so natively
# trained checkpoints decode unchanged; SpeechToText switches to the real
# HF ids (50258/50257) only when an HF checkpoint is ingested.
WHISPER_TINY = AsrConfig(
    n_mels=80, d_model=384, enc_layers=4, dec_layers=4, n_heads=6,
    vocab_size=51865, max_frames=1500, max_text_len=448, dtype="bfloat16")

WHISPER_SMALL = AsrConfig(
    n_mels=80, d_model=768, enc_layers=12, dec_layers=12, n_heads=12,
    vocab_size=51865, max_frames=1500, max_text_len=448, dtype="bfloat16")

# YOLOv8-n operating shape: 640x640 input, 80 classes (reference
# yolo.py:51-87 runs YOLOv8 on webcam frames)
YOLOV8N_SHAPE = DetectorConfig(
    n_classes=80, base_channels=16, image_size=640, stride=16,
    max_detections=300, score_threshold=0.25, dtype="bfloat16")

DETECTOR_TOY = DetectorConfig(
    n_classes=16, base_channels=8, image_size=64, max_detections=8,
    dtype="float32")


# -- analytic FLOP models (for MFU reporting in bench.py) -------------------

def transformer_flops_per_token(config: TransformerConfig,
                                seq_len: int | None = None) -> float:
    """Forward FLOPs per token: 2*params for the matmuls plus the
    attention score/value terms (2 * 2 * L * d per token when seq_len is
    given -- the quadratic part)."""
    d, ff = config.d_model, config.d_ff
    hd = config.head_dim
    attn_proj = 2 * d * (config.n_heads * hd          # wq
                         + 2 * config.n_kv_heads * hd  # wk, wv
                         + config.n_heads * hd)        # wo
    mlp = 2 * d * ff * 3                               # gate, up, down
    per_layer = attn_proj + mlp
    if seq_len:
        per_layer += 2 * 2 * seq_len * d               # qk^T and att@v
    head = 2 * d * config.vocab_size                   # logits
    return config.n_layers * per_layer + head


def asr_flops_per_example(config: AsrConfig, n_frames: int,
                          n_tokens: int) -> float:
    """Encoder over n_frames mel positions + decoder over n_tokens with
    cross-attention; 2*weight-size per matmul, plus attention terms."""
    d = config.d_model
    attn = 8 * d * d
    mlp = 2 * d * (4 * d) * 2
    enc_layer = (attn + mlp) * n_frames + 4 * n_frames * n_frames * d
    dec_layer = ((2 * attn + mlp) * n_tokens
                 + 4 * n_tokens * n_tokens * d
                 + 4 * n_tokens * n_frames * d)
    head = 2 * d * config.vocab_size * n_tokens
    return (config.enc_layers * enc_layer
            + config.dec_layers * dec_layer + head)


def tts_flops_per_example(config, n_chars: int) -> float:
    """chars -> waveform FLOPs: conv stack over upsampled frames + mel
    head + Griffin-Lim's per-iteration STFT/ISTFT pair as DFT matmuls
    (tts.py synthesize)."""
    frames = n_chars * config.frames_per_char
    d = config.d_model
    conv = config.n_conv_layers * 2 * config.kernel_size * d * d * frames
    mel_head = 2 * d * config.n_mels * frames
    bins = config.n_fft // 2 + 1
    griffin = (config.griffin_lim_iters
               * 2 * 2 * frames * config.n_fft * bins)
    return conv + mel_head + griffin


def detector_flops_per_image(config: DetectorConfig) -> float:
    """Conv backbone FLOPs: 2 * k*k * C_in * C_out * H_out * W_out summed
    over the backbone's 8 conv stages + head (detector.py:45-58)."""
    c = config.base_channels
    size = config.image_size
    stages = [  # (c_in, c_out, stride) mirroring init_detector_params
        (3, c, 2), (c, c * 2, 2), (c * 2, c * 2, 1), (c * 2, c * 4, 2),
        (c * 4, c * 4, 1), (c * 4, c * 8, 2), (c * 8, c * 8, 1),
    ]
    total = 0.0
    h = size
    for c_in, c_out, stride in stages:
        h = h // stride
        total += 2 * 9 * c_in * c_out * h * h
    total += 2 * 1 * (c * 8) * (5 + config.n_classes) * h * h  # 1x1 head
    return total
