from .transformer import (                                    # noqa: F401
    TransformerConfig, init_params, param_specs, forward, init_cache,
    cache_specs, decode_step, generate, make_train_step, count_params)
from .asr import (                                            # noqa: F401
    AsrConfig, init_asr_params, asr_param_specs, encode_audio,
    decode_tokens, asr_forward, transcribe)
from .detector import (                                       # noqa: F401
    DetectorConfig, init_detector_params, detect, detector_forward,
    decode_boxes, non_max_suppression)
