from .transformer import (                                    # noqa: F401
    TransformerConfig, init_params, param_specs, forward, init_cache,
    cache_specs, decode_step, generate, generate_stream, make_train_step,
    count_params, quantize_weights_int8, quantized_param_specs,
    init_paged_pool, paged_prefill, paged_decode_step,
    paged_prefill_chunk, paged_verify_step, REMAT_POLICIES,
    resolve_remat_policy)
from .tokenizer import BPETokenizer, train_bpe                # noqa: F401
from .weights import (                                        # noqa: F401
    read_safetensors, write_safetensors, SafetensorsFile, save_pytree,
    load_pytree, load_llama_params, load_whisper_params)
from .configs import (                                        # noqa: F401
    LLAMA3_8B, LLAMA32_1B, LM_TOY, WHISPER_TINY, WHISPER_SMALL,
    YOLOV8N_SHAPE, DETECTOR_TOY, transformer_flops_per_token,
    asr_flops_per_example, detector_flops_per_image)
from .asr import (                                            # noqa: F401
    AsrConfig, init_asr_params, asr_param_specs, encode_audio,
    decode_tokens, asr_forward, make_asr_train_step, transcribe,
    transcribe_audio)
from .detector import (                                       # noqa: F401
    DetectorConfig, init_detector_params, detect, detector_forward,
    decode_boxes, make_detector_train_step, non_max_suppression)
from .yolo import (                                           # noqa: F401
    YoloV8Config, YOLOV8N, YOLO_VARIANTS, init_yolo_params,
    infer_yolov8_config, load_yolov8_params, yolo_forward, yolo_detect)
from .tts import (                                            # noqa: F401
    TTSConfig, init_tts_params, synthesize, synthesize_mel,
    encode_chars, make_tts_train_step)
