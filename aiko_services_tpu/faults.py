# Deterministic fault injection: the measurement substrate for the
# stream fault-tolerance layer.
#
# At the ROADMAP scale (heavy traffic from millions of users) transient
# faults are the steady state, so the retry/dead-letter/circuit-breaker
# machinery in pipeline.py and transfer.py needs a way to be PROVEN, not
# just reasoned about.  This module provides seeded, deterministic
# injection points the engine and the transfer plane consult:
#
#   element_raise    one element call fails (as if process_frame raised)
#   fetch_drop       a transfer-plane fetch attempt dies with a socket
#                    error before dialing
#   reply_blackhole  a process_frame_response for a node is swallowed
#                    (a dead RemoteElement / lost reply)
#   dispatch_delay   extra host latency before an element dispatch
#   connection_drop  an MQTT connection is severed abnormally (consumed
#                    by tests driving the embedded broker)
#   replica_kill     a serving-gateway replica dies abnormally (consumed
#                    by the gateway per routed frame: node= targets the
#                    replica by name, frame=k kills it on the k-th frame
#                    routed to it)
#
# Process-scoped points (the chaos harness: whole processes die, the
# broker partitions -- exercised by `bench.py` `chaos` and
# tests/test_chaos.py):
#
#   process_kill     a whole process dies abnormally: ProcessManager
#                    consults it once per monitor poll per OS child
#                    (node= the process id) and kills the match; chaos
#                    harnesses consult it per tick for VIRTUAL
#                    processes and crash them via Process.crash() /
#                    LoopbackTransport.sever() (LWT fires, no clean
#                    shutdown)
#   broker_partition a client's path to the broker drops BOTH ways for
#                    ms= milliseconds: LoopbackTransport consults it
#                    per publish when `chaos_name` is set (partition +
#                    scheduled heal; ms=0 partitions until heal() is
#                    called manually)
#   registrar_kill   the registrar primary dies abnormally (harness-
#                    consulted like process_kill, but named so a chaos
#                    plan reads as intent: the election/reap path is
#                    the thing under test)
#   transfer_stall   a transfer-plane SERVER answers one accepted
#                    connection only after ms= of silence (a wedged
#                    keeper/producer): the client's socket timeout --
#                    adopt_timeout on the KV-migration paths -- must
#                    bound the caller and degrade to re-prefill
#
# WAN-shaped points (the region fault plane, riding the same seeded
# broker machinery -- exercised by the federated chaos arms and
# tests/test_region.py).  Links are DIRECTED (region, region) pairs,
# written src=us:dst=eu (or node=us>eu); these points default to
# times=-1 because a link's latency/loss is a property of the link,
# not a one-shot event:
#
#   link_latency     every cross-region delivery over the (src, dst)
#                    link is delayed ms= milliseconds (consulted by
#                    the loopback broker at fan-out when publisher and
#                    subscriber carry different `chaos_region`s)
#   link_jitter      adds a DETERMINISTIC extra 0..ms= delay per
#                    delivery, hashed from (seed, link, subscriber,
#                    publish ordinal) -- WAN variance without losing
#                    bit-reproducibility
#   link_loss        cross-region deliveries over the link are dropped
#                    (rate= for lossy links, frame=k for targeted
#                    drops); loss is delivery-side, so an intra-region
#                    subscriber still hears the publish
#   region_partition a whole REGION is severed at once: every client
#                    whose `chaos_region` matches node= partitions
#                    from the broker (both directions, LWT fires) at
#                    its frame=k-th publish, each client consuming its
#                    OWN publish ordinal so one spec severs every
#                    group in the region deterministically; ms=
#                    schedules the heal exactly like broker_partition
#
# Determinism contract: rate-based selection hashes (seed, point, node,
# frame_id) -- the SAME frames are poisoned on every run with the same
# seed, independent of call order, thread timing, or how many other
# injection points fired.  Count-based directives (frame=k, times=n)
# consume deterministically in call order within one injector.
#
# Spec grammar (pipeline parameter `faults` or the AIKO_FAULTS env var):
#
#   spec      := directive (";" directive)*
#   directive := "seed=" int
#              | point (":" key "=" value)*
#   point     := element_raise | fetch_drop | reply_blackhole
#              | dispatch_delay | connection_drop | replica_kill
#              | process_kill | broker_partition | registrar_kill
#              | link_latency | link_loss | link_jitter
#              | region_partition
#   keys      := node=<name> frame=<int> rate=<float 0..1>
#                times=<int, -1 = unlimited> ms=<float>
#                once=<1: each selected frame fails at most once>
#                src=<region> dst=<region>   (link_* points only: the
#                directed link; equivalent to node=<src>><dst>)
#
# Examples:
#   "seed=7;element_raise:node=asr:frame=3:times=1"   transient: frame 3
#                                                     fails once, retries
#                                                     succeed
#   "seed=7;element_raise:node=detector:rate=0.01:once=1"
#                                                     transient 1% faults
#   "seed=7;element_raise:node=detector:rate=0.01:times=-1"
#                                                     permanent 1% faults
#   "fetch_drop:times=1"                              first fetch attempt
#                                                     dies; retry survives
#   "reply_blackhole:node=remote_add:times=1;dispatch_delay:ms=5:rate=0.1"
#
# Cost contract: a pipeline without a spec holds injector None and every
# hot-path hook is one `is not None` check; the bench A/B (bench.py
# --faults) proves the disabled path stays off the hot path.

from __future__ import annotations

import hashlib
import os
import threading

from .analyze.grammar import DirectiveGrammar, Field

__all__ = ["FaultInjector", "FAULTS_GRAMMAR", "create_injector",
           "get_injector", "link_name", "reset_injector"]

_POINTS = ("element_raise", "fetch_drop", "reply_blackhole",
           "dispatch_delay", "connection_drop", "replica_kill",
           "process_kill", "broker_partition", "registrar_kill",
           "transfer_stall", "link_latency", "link_loss",
           "link_jitter", "region_partition")

# WAN points describe standing conditions (a link HAS latency, a
# severed region STAYS severed for every member), so their rules
# default to times=-1 instead of the one-shot default.
_CONTINUOUS_POINTS = frozenset(
    ("link_latency", "link_loss", "link_jitter", "region_partition"))
_LINK_POINTS = frozenset(("link_latency", "link_loss", "link_jitter"))

# The spec grammar above as a declarative table over the shared
# directive-grammar core (analyze/grammar.py): parse and offline lint
# (`aiko lint` AIKO402) validate through the SAME definition, so the
# two can never drift.
_RULE_FIELDS = {
    "node": Field("str"),
    "frame": Field("int", minimum=0),
    "rate": Field("float", minimum=0.0, maximum=1.0),
    "times": Field("int", minimum=-1),
    "ms": Field("float", minimum=0.0),
    "once": Field("flag"),
}
_LINK_FIELDS = dict(_RULE_FIELDS,
                    src=Field("str"), dst=Field("str"))
FAULTS_GRAMMAR = DirectiveGrammar(
    "faults",
    options={"seed": Field("int")},
    heads={point: (_LINK_FIELDS if point in _LINK_POINTS
                   else _RULE_FIELDS)
           for point in _POINTS},
    unknown_head_message="unknown fault point")


def link_name(src, dst) -> str:
    """The canonical node name for a directed (src, dst) region link:
    `us>eu`.  Specs may write src=us:dst=eu or node=us>eu -- both
    normalize here so selection state is shared."""
    return f"{src}>{dst}"


class _Rule:
    """One parsed directive for one injection point."""

    __slots__ = ("node", "frame", "rate", "times", "ms", "once",
                 "fired", "seen", "calls")

    def __init__(self, args: dict, continuous: bool = False):
        self.node = args.get("node")
        src, dst = args.get("src"), args.get("dst")
        if (src is None) != (dst is None):
            raise ValueError(
                "faults: link points need BOTH src= and dst= "
                "(the directed region link), or node=<src>><dst>")
        if src is not None:
            if self.node is not None:
                raise ValueError(
                    "faults: give node= OR src=/dst=, not both")
            self.node = link_name(str(src).strip(), str(dst).strip())
        self.frame = (int(args["frame"]) if "frame" in args else None)
        self.rate = (float(args["rate"]) if "rate" in args else None)
        default_times = -1 if (continuous or self.rate is not None) else 1
        self.times = int(args.get("times", default_times))
        self.ms = float(args.get("ms", 0.0))
        # once=1: each selected (node, frame) fires at most ONCE -- the
        # transient-fault shape (a retry of the same frame succeeds),
        # vs the default where a selected frame fails on every attempt
        self.once = str(args.get("once", "")).lower() in ("1", "true")
        self.fired = 0
        self.seen: set = set()
        # consumed-call ordinal for points with NO frame identity
        # (fetch_drop, connection_drop, reply_blackhole): it stands in
        # for frame_id, so rate= draws vary per call instead of
        # degenerating to a constant, and frame=k targets the k-th
        # call (0-based)
        self.calls = 0

    def exhausted(self) -> bool:
        return self.times >= 0 and self.fired >= self.times


class FaultInjector:
    """Parsed fault plan with per-rule consumption state.  One injector
    per pipeline (from the `faults` pipeline parameter) or per process
    (from AIKO_FAULTS); stats() reports every injection fired, keyed by
    point, so harnesses can reconcile injected vs recovered."""

    def __init__(self, spec: str, seed: int = 0,
                 rules: dict | None = None):
        self.spec = spec
        self.seed = seed
        self._rules: dict[str, list[_Rule]] = rules or {}
        self._lock = threading.Lock()
        self._stats: dict[str, int] = {}

    # -- deterministic selection ---------------------------------------

    def _selected(self, rule: _Rule, point: str, node, frame_id,
                  scope) -> bool:
        """Does this rule target (node, frame_id)?  Rate-based selection
        is a pure function of (seed, point, node, scope, frame_id):
        stable across runs, call order, and interleaving.  `scope` (the
        stream id in the pipeline hooks) decorrelates equal frame ids on
        different streams."""
        if rule.node is not None and node is not None \
                and rule.node != str(node):
            return False
        if rule.frame is not None:
            return frame_id is not None and int(frame_id) == rule.frame
        if rule.rate is not None:
            key = (f"{self.seed}:{point}:{node}:{scope}:"
                   f"{frame_id}").encode()
            digest = hashlib.blake2b(key, digest_size=8).digest()
            draw = int.from_bytes(digest, "big") / float(1 << 64)
            return draw < rule.rate
        return True  # bare directive: every call until times exhausted

    def _fire(self, point: str, node=None, frame_id=None,
              scope="") -> _Rule | None:
        rules = self._rules.get(point)
        if not rules:
            return None
        with self._lock:
            for rule in rules:
                if rule.exhausted():
                    continue
                if (rule.node is not None and node is not None
                        and rule.node != str(node)):
                    # node filter BEFORE the ordinal: other nodes' calls
                    # must not consume this rule's draws, or which call
                    # gets poisoned would depend on interleaving --
                    # breaking the determinism contract
                    continue
                rule_frame_id = frame_id
                if frame_id is None:
                    # identity-less call: the per-rule ordinal is the
                    # frame id (each call is one independent draw)
                    rule_frame_id = rule.calls
                    rule.calls += 1
                if not self._selected(rule, point, node, rule_frame_id,
                                      scope):
                    continue
                if rule.once:
                    key = (str(node), scope, rule_frame_id)
                    if key in rule.seen:
                        continue  # this frame already took its fault
                    rule.seen.add(key)
                rule.fired += 1
                self._stats[point] = self._stats.get(point, 0) + 1
                return rule
        return None

    def _peek(self, point: str, node=None, frame_id=None,
              scope="") -> bool:
        rules = self._rules.get(point)
        if not rules:
            return False
        with self._lock:
            return any(
                not rule.exhausted()
                and self._selected(
                    rule, point, node,
                    rule.calls if frame_id is None else frame_id, scope)
                and not (rule.once
                         and (str(node), scope,
                              rule.calls if frame_id is None
                              else frame_id) in rule.seen)
                for rule in rules)

    # -- injection points (engine-facing) ------------------------------

    def element_raise(self, node, frame_id, scope="") -> bool:
        """Consume: should THIS element call fail?"""
        return self._fire("element_raise", node, frame_id,
                          scope) is not None

    def element_raise_pending(self, node, frame_id, scope="") -> bool:
        """Peek without consuming: is (node, frame_id) poisoned?  The
        micro-batch scheduler uses this to fail the whole-group attempts
        (fused, then chained) without burning the poisoned frame's
        consumable, so the per-frame isolation pass still observes it."""
        return self._peek("element_raise", node, frame_id, scope)

    def fetch_drop(self) -> bool:
        return self._fire("fetch_drop") is not None

    def reply_blackhole(self, node) -> bool:
        return self._fire("reply_blackhole", node) is not None

    def dispatch_delay(self, node, frame_id, scope="") -> float:
        rule = self._fire("dispatch_delay", node, frame_id, scope)
        return rule.ms / 1000.0 if rule is not None else 0.0

    def connection_drop(self) -> bool:
        return self._fire("connection_drop") is not None

    def transfer_stall(self) -> float:
        """Consume: stall THIS transfer-plane connection?  Returns the
        injected server-side delay in SECONDS (0.0 = not fired).
        Consulted by TensorTransferServer once per accepted connection
        -- a keeper/producer that accepts but answers slowly -- so
        `frame=k` stalls the k-th connection (per-rule call ordinal)
        and `rate=` draws once per connection.  The CLIENT's socket
        timeout (fetch/adopt/restore timeout), not the stall, bounds
        the caller: the test contract is that adopt_timeout degrades a
        slow keeper to a local re-prefill instead of wedging the
        engine pump."""
        rule = self._fire("transfer_stall")
        return rule.ms / 1000.0 if rule is not None else 0.0

    def replica_kill(self, replica) -> bool:
        """Consume: should `replica` die now?  Consulted by the serving
        gateway once per frame ROUTED to that replica, so `frame=k`
        kills the replica on its k-th routed frame (0-based, the
        per-rule call ordinal) and `rate=` draws once per routed
        frame.  The node filter keeps other replicas' traffic from
        consuming the rule's ordinal (same determinism contract as
        element_raise)."""
        return self._fire("replica_kill", replica) is not None

    # -- process-scoped points (the chaos harness) ---------------------

    def process_kill(self, process) -> bool:
        """Consume: should the whole process `process` die now?
        ProcessManager consults once per monitor poll per OS child;
        chaos harnesses consult once per tick per virtual process --
        either way `frame=k` kills on the k-th consult for that node
        (the node filter isolates each process's ordinal)."""
        return self._fire("process_kill", process) is not None

    def broker_partition(self, client) -> float:
        """Consume: partition `client` from the broker?  Returns the
        partition duration in SECONDS (0.0 = not fired; a fired rule
        with no ms= means "until heal() is called").  Consulted by
        LoopbackTransport once per publish when its `chaos_name` is
        set, so `frame=k` partitions on the client's k-th publish."""
        rule = self._fire("broker_partition", client)
        if rule is None:
            return 0.0
        return rule.ms / 1000.0 if rule.ms > 0 else -1.0

    # -- WAN-shaped points (the region fault plane) --------------------

    def link_drop(self, src, dst, frame_id=None, scope="") -> bool:
        """Consume: drop THIS cross-region delivery over the directed
        (src, dst) link?  The broker consults at fan-out, passing the
        publisher's publish ordinal as `frame_id` and the subscriber's
        name as `scope`, so rate= draws are a pure function of (seed,
        link, subscriber, publish ordinal) -- identical firing
        sequences on every run regardless of dispatch-thread
        interleaving."""
        return self._fire("link_loss", link_name(src, dst), frame_id,
                          scope) is not None

    def link_delay(self, src, dst, frame_id=None, scope="") -> float:
        """Consume: extra delivery latency in SECONDS over the (src,
        dst) link -- link_latency's fixed ms= plus link_jitter's
        deterministic 0..ms= fraction, hashed from (seed, link, scope,
        frame_id) so WAN variance stays bit-reproducible."""
        link = link_name(src, dst)
        delay_ms = 0.0
        rule = self._fire("link_latency", link, frame_id, scope)
        if rule is not None:
            delay_ms += rule.ms
        jitter = self._fire("link_jitter", link, frame_id, scope)
        if jitter is not None and jitter.ms > 0:
            key = (f"{self.seed}:link_jitter:{link}:{scope}:"
                   f"{frame_id}").encode()
            digest = hashlib.blake2b(key, digest_size=8).digest()
            frac = int.from_bytes(digest, "big") / float(1 << 64)
            delay_ms += jitter.ms * frac
        return delay_ms / 1000.0

    def region_partition(self, region, frame_id=None, scope="") -> float:
        """Consume: sever this client's whole REGION from the broker?
        Same return contract as broker_partition (seconds; -1.0 =
        until heal).  Each client in the region consults with its OWN
        publish ordinal as `frame_id` and its name as `scope`, so one
        `region_partition:node=eu:frame=0` spec severs EVERY eu client
        at its first publish -- the region dies as a unit, and the
        firing sequence is identical on every run."""
        rule = self._fire("region_partition", region, frame_id, scope)
        if rule is None:
            return 0.0
        return rule.ms / 1000.0 if rule.ms > 0 else -1.0

    def registrar_kill(self, registrar) -> bool:
        """Consume: should the registrar `registrar` die now?  Same
        shape as process_kill; a separate point so one chaos spec can
        schedule gateway, replica, and registrar deaths independently
        without sharing consumption ordinals."""
        return self._fire("registrar_kill", registrar) is not None

    def stats(self) -> dict:
        with self._lock:
            return dict(self._stats)


def create_injector(spec) -> FaultInjector | None:
    """Parse a fault spec; None/empty spec means no injection (the
    production state: every hook collapses to one is-None check)."""
    if not spec:
        return None
    spec = str(spec)
    parsed = FAULTS_GRAMMAR.parse(spec)
    seed = int(parsed.options.get("seed", 0))
    rules: dict[str, list[_Rule]] = {}
    for head, args in parsed.directives:
        rules.setdefault(head, []).append(
            _Rule(args, continuous=head in _CONTINUOUS_POINTS))
    return FaultInjector(spec, seed=seed, rules=rules)


# Process-global injector: points with no pipeline context (transfer
# plane fetches, transport tests) consult this one, configured by the
# AIKO_FAULTS env var and cached after first read.
_GLOBAL: FaultInjector | None = None
_GLOBAL_READ = False
_GLOBAL_LOCK = threading.Lock()


def get_injector() -> FaultInjector | None:
    global _GLOBAL, _GLOBAL_READ
    if _GLOBAL_READ:
        # lock-free fast path: the plan is fixed after first read, and
        # this sits on the tensor-fetch hot path -- concurrent fetches
        # must not serialize on a mutex for a constant
        return _GLOBAL
    with _GLOBAL_LOCK:
        if not _GLOBAL_READ:
            _GLOBAL = create_injector(os.environ.get("AIKO_FAULTS"))
            _GLOBAL_READ = True
        return _GLOBAL


def reset_injector() -> None:
    """Forget the cached AIKO_FAULTS plan (tests re-read the env)."""
    global _GLOBAL, _GLOBAL_READ
    with _GLOBAL_LOCK:
        _GLOBAL = None
        _GLOBAL_READ = False
