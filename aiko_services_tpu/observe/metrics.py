# Metrics registry: counters, gauges, and log-bucketed histograms with
# MERGEABLE snapshots, serialized over the existing S-expression / EC
# machinery.
#
# No reference counterpart -- the reference's only observability is the
# log-topic Recorder (reference: src/aiko_services/main/recorder.py:50-96)
# and ad-hoc per-frame timing floats.  Here every hot-path instrument is a
# first-class metric a Recorder, dashboard, or bench harness can consume
# live, and snapshots from MANY processes merge associatively into one
# fleet view (Prometheus-style, but carried by the framework's own
# control plane instead of an HTTP scrape).
#
# Cost contract (the pipeline instruments its per-frame hot path with
# these): Counter.inc is one int add, Gauge.set one assignment, and
# Histogram.record one bisect into a precomputed geometric ladder --
# nothing allocates, nothing locks (GIL-racy increments can at worst
# drop a count; instruments are diagnostics, not ledgers).
#
# Wire format: `generate("metrics", [source, snapshot])` -- the snapshot
# is a nested keyword dict, so it rides any transport the control plane
# rides and shows up readable in `mosquitto_sub`.  The S-expression
# parser returns numbers as strings; `snapshot_from_wire` restores the
# numeric types, making to-wire/from-wire a lossless round trip for the
# supported value domain.

from __future__ import annotations

from bisect import bisect_left
from collections import deque

from ..utils import generate, parse_number

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry",
    "SlidingWindow", "get_registry", "merge_snapshots",
    "parse_metrics_payload", "snapshot_from_wire", "snapshot_quantile",
]

# Geometric bucket ladder for timing histograms: 10 us doubling up to
# ~84 s (24 bounds -> 25 buckets with the overflow).  One ladder for
# every histogram keeps merges trivially associative: identical bounds
# mean bucket-wise addition, in any grouping.
DEFAULT_BOUNDS = tuple(1e-5 * (2.0 ** i) for i in range(24))


class Counter:
    """Monotonic event count; .inc(n) is one int add."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def inc(self, amount: int = 1) -> None:
        self.value += amount


class Gauge:
    """Last-write-wins instantaneous value."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, value) -> None:
        self.value = float(value)


class Histogram:
    """Log-bucketed distribution: one bisect per record, fixed storage.

    Snapshots carry (count, sum, min, max, per-bucket counts); two
    snapshots with the same bounds merge by element-wise addition, so
    merge is associative and commutative -- partial aggregations from
    different processes/windows combine in any order."""

    __slots__ = ("bounds", "buckets", "count", "total", "low", "high")

    def __init__(self, bounds=DEFAULT_BOUNDS):
        self.bounds = tuple(bounds)
        self.buckets = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.total = 0.0
        self.low = None
        self.high = None

    def record(self, value) -> None:
        value = float(value)
        self.buckets[bisect_left(self.bounds, value)] += 1
        self.count += 1
        self.total += value
        if self.low is None or value < self.low:
            self.low = value
        if self.high is None or value > self.high:
            self.high = value

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    def quantile(self, q: float) -> float:
        """Estimated q-quantile by linear interpolation inside the
        log bucket holding the rank -- the ONE quantile-extraction
        implementation (dashboard, gateway summary, and `aiko tune`
        all read it; each used to re-derive quantiles ad hoc).
        Empty -> 0.0; q<=0 -> observed min; q>=1 -> observed max;
        interior bucket edges are clamped to the observed min/max so a
        single-bucket histogram interpolates within real data, not the
        full geometric bucket span."""
        return snapshot_quantile(self.snapshot(), q, self.bounds)

    def snapshot(self) -> dict:
        return {"count": self.count, "sum": self.total,
                "min": self.low if self.low is not None else 0.0,
                "max": self.high if self.high is not None else 0.0,
                "buckets": list(self.buckets)}


class SlidingWindow:
    """Windowed deltas of CUMULATIVE counters over N-second buckets.

    Counters only grow, so "burn over the last W seconds" needs a
    baseline: sample() records the current cumulative values into a
    coarse bucket ring (one retained sample per `bucket_s` slot), and
    delta() reads latest-minus-trailing-edge.  Both the autopilot's
    act/back-off gate and the dashboard `slo:` row consume this -- the
    cumulative-since-start ratio goes stale as a health signal on long
    runs (an hour of 100% attainment hides a minute of 0%).

    The caller supplies `now` (monotonic seconds), like TokenBucket, so
    tests drive the window deterministically.  Samples older than the
    window are pruned except the newest one at-or-before the trailing
    edge, which serves as the baseline."""

    __slots__ = ("window_s", "bucket_s", "_samples")

    def __init__(self, window_s: float = 60.0,
                 bucket_s: float | None = None):
        self.window_s = max(float(window_s), 1e-9)
        # ~12 buckets per window by default: coarse enough that a
        # per-frame sampler costs nothing, fine enough that the window
        # edge moves smoothly
        self.bucket_s = (max(float(bucket_s), 1e-9)
                         if bucket_s is not None
                         else max(self.window_s / 12.0, 1e-9))
        self._samples: deque = deque()   # (bucket, now, {name: value})

    def sample(self, now: float, values: dict) -> None:
        """Record cumulative `values` at time `now`.  Within one bucket
        slot the LATEST sample wins (the slot's closing totals)."""
        now = float(now)
        bucket = int(now // self.bucket_s)
        snapshot = {name: float(value)
                    for name, value in values.items()}
        if self._samples and self._samples[-1][0] == bucket:
            self._samples[-1] = (bucket, now, snapshot)
        else:
            self._samples.append((bucket, now, snapshot))
        edge = now - self.window_s
        while len(self._samples) >= 2 and self._samples[1][1] <= edge:
            self._samples.popleft()

    def delta(self, name: str) -> float:
        """latest - baseline for one counter; 0.0 with fewer than two
        samples (no window to difference yet) or an unseen name."""
        if len(self._samples) < 2:
            return 0.0
        latest = self._samples[-1][2].get(name, 0.0)
        baseline = self._samples[0][2].get(name, 0.0)
        return max(latest - baseline, 0.0)

    def span(self) -> float:
        """Seconds actually covered (<= window_s during warm-up)."""
        if len(self._samples) < 2:
            return 0.0
        return self._samples[-1][1] - self._samples[0][1]

    def rate(self, name: str) -> float:
        span = self.span()
        return self.delta(name) / span if span > 0 else 0.0

    def burn(self, miss_name: str, ok_name: str) -> float | None:
        """Windowed burn rate miss/(ok+miss); None when the window saw
        no traffic at all (no signal is different from zero burn)."""
        miss = self.delta(miss_name)
        ok = self.delta(ok_name)
        total = ok + miss
        if total <= 0:
            return None
        return miss / total


def snapshot_quantile(snapshot: dict, q: float,
                      bounds=None) -> float:
    """Quantile extraction from a histogram SNAPSHOT dict (the shape
    that rides the wire / the trace metadata): the same estimate as
    Histogram.quantile, usable by consumers that only hold the
    serialized form.  `bounds` defaults to DEFAULT_BOUNDS when the
    bucket count matches it; snapshots of custom-ladder histograms
    must pass their bounds explicitly."""
    count = int(snapshot.get("count", 0) or 0)
    if count <= 0:
        return 0.0
    low = float(snapshot.get("min", 0.0))
    high = float(snapshot.get("max", 0.0))
    q = float(q)
    if q <= 0.0:
        return low
    if q >= 1.0:
        return high
    buckets = snapshot.get("buckets") or []
    if bounds is None:
        if len(buckets) == len(DEFAULT_BOUNDS) + 1:
            bounds = DEFAULT_BOUNDS
        else:
            # unknown ladder: the only defensible estimate is the
            # observed range itself
            return low + (high - low) * q
    rank = q * count
    cumulative = 0
    for index, bucket_count in enumerate(buckets):
        if not bucket_count:
            continue
        if cumulative + bucket_count >= rank:
            edge_low = bounds[index - 1] if index > 0 else 0.0
            edge_high = (bounds[index] if index < len(bounds)
                         else high)
            # clamp to observed data: a single-bucket histogram must
            # not report values outside [min, max]
            edge_low = max(edge_low, low)
            edge_high = max(min(edge_high, high), edge_low)
            fraction = (rank - cumulative) / bucket_count
            return edge_low + (edge_high - edge_low) * fraction
        cumulative += bucket_count
    return high


def _merge_histogram(left: dict, right: dict) -> dict:
    left_buckets = list(left.get("buckets") or [])
    right_buckets = list(right.get("buckets") or [])
    if len(left_buckets) < len(right_buckets):
        left_buckets += [0] * (len(right_buckets) - len(left_buckets))
    for index, value in enumerate(right_buckets):
        left_buckets[index] += value
    left_count = left.get("count", 0)
    right_count = right.get("count", 0)
    # min/max of an EMPTY side must not poison the merge with its 0.0
    # placeholder -- an all-empty merge stays at the placeholder
    if not left_count:
        low, high = right.get("min", 0.0), right.get("max", 0.0)
    elif not right_count:
        low, high = left.get("min", 0.0), left.get("max", 0.0)
    else:
        low = min(left.get("min", 0.0), right.get("min", 0.0))
        high = max(left.get("max", 0.0), right.get("max", 0.0))
    return {"count": left_count + right_count,
            "sum": left.get("sum", 0.0) + right.get("sum", 0.0),
            "min": low, "max": high, "buckets": left_buckets}


def merge_snapshots(left: dict, right: dict) -> dict:
    """Associative merge of two registry snapshots: counters add,
    gauges last-write-win (right side), histograms add bucket-wise."""
    counters = dict(left.get("counters") or {})
    for name, value in (right.get("counters") or {}).items():
        counters[name] = counters.get(name, 0) + value
    gauges = dict(left.get("gauges") or {})
    gauges.update(right.get("gauges") or {})
    histograms = {name: dict(value) for name, value
                  in (left.get("histograms") or {}).items()}
    for name, value in (right.get("histograms") or {}).items():
        if name in histograms:
            histograms[name] = _merge_histogram(histograms[name], value)
        else:
            histograms[name] = dict(value)
    return {"counters": counters, "gauges": gauges,
            "histograms": histograms}


_UNSAFE_NAME_CHARS = set(' \t\r\n()"')


def _safe_name(name: str) -> str:
    """Instrument names become UNQUOTED dict keys on the S-expression
    wire; whitespace/parens in a name (e.g. an element named with a
    space) would mis-tokenize the whole snapshot on the consumer side,
    so they are normalized to '_' at registration."""
    if any(ch in _UNSAFE_NAME_CHARS for ch in name):
        return "".join("_" if ch in _UNSAFE_NAME_CHARS else ch
                       for ch in name)
    return name


class MetricsRegistry:
    """Named instruments, get-or-create; snapshot() is wire-safe."""

    def __init__(self):
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        instrument = self._counters.get(name)
        if instrument is None:
            name = _safe_name(name)
            instrument = self._counters.get(name)
            if instrument is None:
                instrument = self._counters[name] = Counter()
        return instrument

    def gauge(self, name: str) -> Gauge:
        instrument = self._gauges.get(name)
        if instrument is None:
            name = _safe_name(name)
            instrument = self._gauges.get(name)
            if instrument is None:
                instrument = self._gauges[name] = Gauge()
        return instrument

    def has_gauge(self, name: str) -> bool:
        """Existence probe WITHOUT the get-or-create side effect of
        gauge() -- for consumers that only want to know whether a
        subsystem (e.g. the decode engine) registered itself."""
        return name in self._gauges or _safe_name(name) in self._gauges

    def histogram(self, name: str, bounds=DEFAULT_BOUNDS) -> Histogram:
        instrument = self._histograms.get(name)
        if instrument is None:
            name = _safe_name(name)
            instrument = self._histograms.get(name)
            if instrument is None:
                instrument = self._histograms[name] = Histogram(bounds)
        return instrument

    def snapshot(self) -> dict:
        # list() the items: other threads (transfer server, mqtt
        # network loop) may register a first-time instrument while the
        # export timer snapshots -- a live-dict iteration would raise
        # mid-publish and lose the whole interval
        return {
            "counters": {name: counter.value for name, counter
                         in list(self._counters.items())},
            "gauges": {name: gauge.value for name, gauge
                       in list(self._gauges.items())},
            "histograms": {name: histogram.snapshot() for name, histogram
                           in list(self._histograms.items())},
        }

    def to_payload(self, source: str) -> str:
        """One `(metrics source snapshot)` S-expression payload."""
        return generate("metrics", [source, self.snapshot()])


def snapshot_from_wire(value) -> dict:
    """Restore a parsed wire snapshot's numeric types: the S-expression
    parser returns atoms as strings and renders empty dicts as empty
    lists; this walks the structure back to the snapshot() shape."""
    if not isinstance(value, dict):
        return {"counters": {}, "gauges": {}, "histograms": {}}

    def section(name):
        part = value.get(name)
        return part if isinstance(part, dict) else {}

    counters = {name: int(parse_number(item, 0))
                for name, item in section("counters").items()}
    gauges = {name: float(parse_number(item, 0.0))
              for name, item in section("gauges").items()}
    histograms = {}
    for name, item in section("histograms").items():
        if not isinstance(item, dict):
            continue
        buckets = item.get("buckets")
        histograms[name] = {
            "count": int(parse_number(item.get("count"), 0)),
            "sum": float(parse_number(item.get("sum"), 0.0)),
            "min": float(parse_number(item.get("min"), 0.0)),
            "max": float(parse_number(item.get("max"), 0.0)),
            "buckets": [int(parse_number(entry, 0)) for entry in buckets]
            if isinstance(buckets, list) else [],
        }
    return {"counters": counters, "gauges": gauges,
            "histograms": histograms}


def parse_metrics_payload(payload):
    """Decode one `(metrics source snapshot)` wire payload into
    (source, snapshot) -- the ONE definition of the consumer-side
    contract (Recorder and dashboard both use it).  Returns None for
    anything that is not a well-formed metrics payload."""
    from ..utils import parse
    try:
        command, parameters = parse(
            payload if isinstance(payload, (str, bytes))
            else str(payload))
    except ValueError:
        return None
    if command != "metrics" or len(parameters) < 2:
        return None
    return str(parameters[0]), snapshot_from_wire(parameters[1])


# Process-global registry: instruments that have no pipeline context
# (tensor transfer plane, MQTT client) record here; the pipeline's
# periodic export merges it into the published snapshot.
_GLOBAL = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _GLOBAL
