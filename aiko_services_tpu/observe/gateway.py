# GatewayTelemetry: the serving gateway's observability seam.
#
# Mirrors PipelineTelemetry's shape (one registry per gateway, hot-path
# instrument handles resolved once, a periodic snapshot publish on
# `{topic_path}/metrics` plus a compact EC-share summary) but records
# the SERVING-TIER vocabulary: admission decisions (admitted / shed,
# per priority), routing (frames routed, per-replica), backpressure
# (parked queue depth per priority, throttle transitions), and
# failover (replica deaths, streams migrated).  The admitted-latency
# histogram measures submit -> response through the whole tier -- the
# number an SLO is written against.

from __future__ import annotations

from ..utils import get_logger
from .metrics import MetricsRegistry

__all__ = ["GatewayTelemetry"]

_LOGGER = get_logger("gateway_telemetry")

DEFAULT_METRICS_INTERVAL = 10.0


class GatewayTelemetry:
    def __init__(self, gateway, enabled: bool = True,
                 interval: float = DEFAULT_METRICS_INTERVAL):
        self.gateway = gateway
        self.enabled = enabled
        self.registry = MetricsRegistry()
        registry = self.registry
        self.admitted = registry.counter("gateway.admitted")
        self.shed_streams = registry.counter("gateway.shed_streams")
        self.shed_frames = registry.counter("gateway.shed_frames")
        self.routed = registry.counter("gateway.routed")
        self.completed = registry.counter("gateway.completed")
        self.released = registry.counter("gateway.released")
        self.duplicates = registry.counter("gateway.duplicates")
        self.throttled = registry.counter("gateway.throttled")
        self.unthrottled = registry.counter("gateway.unthrottled")
        self.failovers = registry.counter("gateway.failovers")
        self.replica_deaths = registry.counter("gateway.replica_deaths")
        self.replicas = registry.gauge("gateway.replicas")
        self.parked = registry.gauge("gateway.parked")
        self.latency = registry.histogram("gateway.admit_latency_s")
        # elastic fleet (serve/autoscale.py): pool occupancy, scale
        # decisions, and the bring-up number the warm-start work
        # optimizes -- spawn decision -> replica serving its first frame
        self.pool_size = registry.gauge("gateway.pool_size")
        self.scale_ups = registry.counter("gateway.scale_up")
        self.scale_downs = registry.counter("gateway.scale_down")
        # disaggregated serving (serve/disagg.py): prefill-hop routing
        # plus the two outcomes -- a KV handoff forwarded to the decode
        # pool, or a degradation to local prefill (pool empty, prefill
        # error, or a parked frame whose handoff keys would expire)
        self.prefill_routed = registry.counter("gateway.prefill_routed")
        self.kv_migrations = registry.counter("gateway.kv_migrations")
        self.prefill_fallbacks = registry.counter(
            "gateway.prefill_fallbacks")
        # warm KV failover (decode/checkpoint.py): migrated streams
        # whose replay was deferred by the recovery_rate pacing window
        self.recovery_paced = registry.counter("gateway.recovery_paced")
        self.time_to_healthy = registry.histogram(
            "gateway.time_to_healthy_ms")
        self.warm_spawns = registry.counter("gateway.spawns_warm")
        self.cold_spawns = registry.counter("gateway.spawns_cold")
        self.last_time_to_healthy_ms: float | None = None
        # crash consistency (serve/journal.py): HA takeovers and the
        # journal's write/replay accounting -- `takeover_ms` is the
        # recovery bound the chaos bench publishes (standby promote ->
        # every journaled stream re-pinned)
        self.takeovers = registry.counter("gateway.takeovers")
        self.takeover_ms = registry.histogram("gateway.takeover_ms")
        self.last_takeover_ms: float | None = None
        self.journal_appends = registry.counter("gateway.journal_appends")
        self.journal_entries = registry.gauge("gateway.journal_entries")
        self.journal_replayed = registry.counter(
            "gateway.journal_replayed")
        self.journal_dropped_stale = registry.counter(
            "gateway.journal_dropped_stale")
        self._interval = interval
        self._timer = None
        if self.enabled and interval > 0:
            self._timer = self._publish_snapshot
            gateway.process.event.add_timer_handler(self._timer, interval)

    def record_queue_depths(self, depths: dict) -> None:
        """Parked-queue occupancy PER PRIORITY (gauge family
        `gateway.queue_depth:p{n}`): overload triage needs to see WHICH
        priorities are waiting, not only the total."""
        if not self.enabled:
            return
        for priority, depth in depths.items():
            self.registry.gauge(
                f"gateway.queue_depth:p{priority}").set(depth)

    def record_replica_routed(self, replica_name: str) -> None:
        if not self.enabled:
            return
        self.registry.counter(f"gateway.routed:{replica_name}").inc()

    def record_spawn(self, time_to_healthy_ms: float,
                     warm: bool) -> None:
        """One finished replica bring-up: decision -> healthy, labeled
        warm (sibling hand-off + compile-cache) or cold."""
        self.time_to_healthy.record(time_to_healthy_ms)
        self.last_time_to_healthy_ms = round(time_to_healthy_ms, 2)
        (self.warm_spawns if warm else self.cold_spawns).inc()

    def record_takeover(self, takeover_ms: float) -> None:
        """One HA takeover: standby promoted, journal adopted, streams
        re-pinned."""
        self.takeovers.inc()
        self.takeover_ms.record(takeover_ms)
        self.last_takeover_ms = round(takeover_ms, 2)

    def snapshot(self) -> dict:
        return self.registry.snapshot()

    def summary(self) -> dict:
        """Compact scalars for the EC share / dashboards.  Admit-latency
        quantiles come from the ONE shared Histogram.quantile helper
        (the same estimate `aiko tune` and the dashboard read) instead
        of an ad-hoc re-derivation."""
        summary = {
            "admitted": self.admitted.value,
            "shed_streams": self.shed_streams.value,
            "shed_frames": self.shed_frames.value,
            "routed": self.routed.value,
            "completed": self.completed.value,
            "released": self.released.value,
            "throttled": self.throttled.value,
            "failovers": self.failovers.value,
            "replica_deaths": self.replica_deaths.value,
            "replicas": self.replicas.value,
            "parked": self.parked.value,
            "pool_size": self.pool_size.value,
            "scale_ups": self.scale_ups.value,
            "scale_downs": self.scale_downs.value,
        }
        if self.prefill_routed.value:
            summary["prefill_routed"] = self.prefill_routed.value
            summary["kv_migrations"] = self.kv_migrations.value
            summary["prefill_fallbacks"] = self.prefill_fallbacks.value
        if self.recovery_paced.value:
            summary["recovery_paced"] = self.recovery_paced.value
        if self.latency.count:
            summary["admit_latency_p50_ms"] = round(
                self.latency.quantile(0.5) * 1000, 3)
            summary["admit_latency_p99_ms"] = round(
                self.latency.quantile(0.99) * 1000, 3)
        if self.last_time_to_healthy_ms is not None:
            summary["time_to_healthy_ms"] = self.last_time_to_healthy_ms
        autoscaler = getattr(self.gateway, "autoscaler", None)
        if autoscaler is not None:
            summary["pool"] = self.gateway.pool_snapshot()
            summary["pending_spawns"] = autoscaler.pending
        journal = getattr(self.gateway, "journal", None)
        if journal is not None:
            ha = {
                "role": getattr(self.gateway, "role", "single"),
                "backend": journal.backend.kind,
                "journal_entries": self.journal_entries.value,
                "journal_appends": self.journal_appends.value,
                "replayed": self.journal_replayed.value,
                "dropped_stale": self.journal_dropped_stale.value,
                "takeovers": self.takeovers.value,
            }
            if self.last_takeover_ms is not None:
                ha["takeover_ms"] = self.last_takeover_ms
            summary["ha"] = ha
        return summary

    def _publish_snapshot(self) -> None:
        gateway = self.gateway
        try:
            from ..utils import generate
            gateway.process.publish(
                f"{gateway.topic_path}/metrics",
                generate("metrics",
                         [gateway.topic_path, self.snapshot()]))
            if gateway.ec_producer is not None:
                gateway.ec_producer.update("metrics", self.summary())
        except Exception as error:  # export must never kill the gateway
            _LOGGER.warning("gateway metrics publish failed: %s", error)

    def stop(self) -> None:
        if self._timer is not None:
            self.gateway.process.event.remove_timer_handler(self._timer)
            self._timer = None
            self._publish_snapshot()
