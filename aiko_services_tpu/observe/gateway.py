# GatewayTelemetry: the serving gateway's observability seam.
#
# Mirrors PipelineTelemetry's shape (one registry per gateway, hot-path
# instrument handles resolved once, a periodic snapshot publish on
# `{topic_path}/metrics` plus a compact EC-share summary) but records
# the SERVING-TIER vocabulary: admission decisions (admitted / shed,
# per priority), routing (frames routed, per-replica), backpressure
# (parked queue depth per priority, throttle transitions), and
# failover (replica deaths, streams migrated).  The admitted-latency
# histogram measures submit -> response through the whole tier -- the
# number an SLO is written against.
#
# Fleet tracing: the gateway is the ROOT-SPAN OWNER of every admitted
# frame's distributed trace.  `frame_begin` mints the trace id, the
# gateway's own spans (admit-wait, route decision, shed/throttle,
# failover replay -- see the taxonomy in observe/trace.py) accumulate
# on it, and the propagated context rides the frame data to every
# replica so their spans continue the SAME trace.  `export_trace` /
# `chrome_events` / `trace_metadata` mirror PipelineTelemetry's
# surface, so bench.py harvests a gateway exactly like a pipeline and
# `aiko trace merge` joins both on one timeline.

from __future__ import annotations


from ..utils import get_logger, monotonic
from .metrics import MetricsRegistry, SlidingWindow
from .trace import Tracer, now_us, to_us, trace_metadata

__all__ = ["GatewayTelemetry"]

_LOGGER = get_logger("gateway_telemetry")

DEFAULT_METRICS_INTERVAL = 10.0
# per-stream end-to-end decomposition entries kept in the summary: the
# EC share is a compact view, not a database (totals always ride)
DECOMPOSITION_STREAM_CAP = 32
# default sliding window for SLO burn: long enough to smooth one slow
# frame, short enough that the dashboard row is a LIVE health signal
DEFAULT_BURN_WINDOW_S = 60.0


class GatewayTelemetry:
    def __init__(self, gateway, enabled: bool = True,
                 interval: float = DEFAULT_METRICS_INTERVAL):
        self.gateway = gateway
        self.enabled = enabled
        self.registry = MetricsRegistry()
        self.tracer = Tracer()
        # per-stream end-to-end decomposition accumulators (seconds):
        # admit + route + queue + prefill + decode + emit -- where each
        # admitted stream's latency went, published in the summary/EC
        # share and rendered by the dashboard gateway plugin.  A
        # destroyed stream's stages fold into the persistent fleet
        # total, so the aggregate survives stream churn
        self._decomposition: dict[str, dict] = {}
        self._decomposition_total: dict[str, float] = {}
        registry = self.registry
        self.admitted = registry.counter("gateway.admitted")
        self.shed_streams = registry.counter("gateway.shed_streams")
        self.shed_frames = registry.counter("gateway.shed_frames")
        self.routed = registry.counter("gateway.routed")
        self.completed = registry.counter("gateway.completed")
        self.released = registry.counter("gateway.released")
        self.duplicates = registry.counter("gateway.duplicates")
        self.throttled = registry.counter("gateway.throttled")
        self.unthrottled = registry.counter("gateway.unthrottled")
        self.failovers = registry.counter("gateway.failovers")
        self.replica_deaths = registry.counter("gateway.replica_deaths")
        self.replicas = registry.gauge("gateway.replicas")
        self.parked = registry.gauge("gateway.parked")
        self.latency = registry.histogram("gateway.admit_latency_s")
        # elastic fleet (serve/autoscale.py): pool occupancy, scale
        # decisions, and the bring-up number the warm-start work
        # optimizes -- spawn decision -> replica serving its first frame
        self.pool_size = registry.gauge("gateway.pool_size")
        self.scale_ups = registry.counter("gateway.scale_up")
        self.scale_downs = registry.counter("gateway.scale_down")
        # disaggregated serving (serve/disagg.py): prefill-hop routing
        # plus the two outcomes -- a KV handoff forwarded to the decode
        # pool, or a degradation to local prefill (pool empty, prefill
        # error, or a parked frame whose handoff keys would expire)
        self.prefill_routed = registry.counter("gateway.prefill_routed")
        self.kv_migrations = registry.counter("gateway.kv_migrations")
        self.prefill_fallbacks = registry.counter(
            "gateway.prefill_fallbacks")
        # prefix-affinity routing (decode/prefix.py): hinted stream
        # placements that landed on a replica already holding the
        # stream's prefix chain head vs ones that could not (holder
        # saturated / draining / not yet warm) -- the A/B evidence the
        # prefix_cache bench compares across its affinity arms
        self.affinity_hits = registry.counter("gateway.affinity_hits")
        self.affinity_misses = registry.counter(
            "gateway.affinity_misses")
        # warm KV failover (decode/checkpoint.py): migrated streams
        # whose replay was deferred by the recovery_rate pacing window,
        # plus the LIVE count of cohorts still parked (decremented when
        # a cohort replays, its stream dies, or its stream is destroyed
        # -- the leak the destroy-while-paced regression test watches)
        self.recovery_paced = registry.counter("gateway.recovery_paced")
        self.recovery_paced_pending = registry.gauge(
            "gateway.recovery_paced_pending")
        # region-aware federation (serve/federation.py): streams
        # adopted from a LOST group's journal onto this survivor, and
        # the region-affinity outcome of every region-declaring stream
        # admission (did placement land in the client's region?)
        self.region_migrations = registry.counter(
            "gateway.region_migrations")
        self.region_affinity_hits = registry.counter(
            "gateway.region_affinity_hits")
        self.region_affinity_misses = registry.counter(
            "gateway.region_affinity_misses")
        self.time_to_healthy = registry.histogram(
            "gateway.time_to_healthy_ms")
        self.warm_spawns = registry.counter("gateway.spawns_warm")
        self.cold_spawns = registry.counter("gateway.spawns_cold")
        self.last_time_to_healthy_ms: float | None = None
        # crash consistency (serve/journal.py): HA takeovers and the
        # journal's write/replay accounting -- `takeover_ms` is the
        # recovery bound the chaos bench publishes (standby promote ->
        # every journaled stream re-pinned)
        self.takeovers = registry.counter("gateway.takeovers")
        self.takeover_ms = registry.histogram("gateway.takeover_ms")
        self.last_takeover_ms: float | None = None
        self.journal_appends = registry.counter("gateway.journal_appends")
        self.journal_entries = registry.gauge("gateway.journal_entries")
        self.journal_replayed = registry.counter(
            "gateway.journal_replayed")
        self.journal_dropped_stale = registry.counter(
            "gateway.journal_dropped_stale")
        # windowed SLO burn (observe/metrics.SlidingWindow): the
        # cumulative attainment/burn ratio goes stale as a health
        # signal on long runs, so the autopilot gate and the dashboard
        # `slo:` row both read burn over THIS window instead
        self.slo_window = SlidingWindow(DEFAULT_BURN_WINDOW_S)
        # per-tick summary the serve/autopilot.py loop stages for the
        # EC share (None until an autopilot is attached and has ticked)
        self.autopilot_summary: dict | None = None
        self._interval = interval
        self._timer = None
        if self.enabled and interval > 0:
            self._timer = self._publish_snapshot
            gateway.process.event.add_timer_handler(self._timer, interval)

    # -- fleet tracing: gateway root spans ---------------------------------

    def frame_begin(self, stream_id: str, frame_id: int):
        """Mint the ROOT trace for one admitted frame (the gateway owns
        the fleet-wide trace id); returns None with telemetry off, so
        the wire payload then carries no trace-context bytes at all."""
        if not self.enabled:
            return None
        return self.tracer.begin(stream_id, frame_id)

    def frame_done(self, trace, status: str = "ok") -> None:
        if trace is not None:
            self.tracer.finish(trace, status=status)

    def record_route(self, trace, start_s: float, replica_name: str,
                     pool: str = "decode") -> None:
        """The placement decision for one dispatched frame."""
        if trace is not None:
            trace.span("route:gateway", "gateway", to_us(start_s),
                       {"replica": replica_name, "pool": pool})

    def record_admit_wait(self, trace) -> float:
        """Admit-wait: frame submit -> FIRST replica dispatch.  Covers
        the parked-queue wait (zero-ish for an immediately dispatchable
        frame); THE span the admission-bound floor classifies on.
        Returns the elapsed seconds for the queue-stage decomposition."""
        if trace is None:
            return 0.0
        elapsed_us = now_us() - trace.start_us
        trace.span("admit:gateway", "gateway", trace.start_us)
        return elapsed_us / 1e6

    def record_shed_span(self, trace, reason: str) -> None:
        if trace is not None:
            trace.instant("shed:gateway", "gateway", {"reason": reason})

    def record_shed_stream(self, stream_id: str, reason: str) -> None:
        """A whole STREAM was shed at admission (no frame trace exists
        yet): a global gateway-lane instant."""
        if self.enabled:
            self.tracer.instant_global(
                "shed:gateway", "gateway",
                {"stream": stream_id, "reason": reason})

    def record_throttle_span(self, rate: float) -> None:
        if self.enabled:
            self.tracer.instant_global("throttle:gateway", "gateway",
                                       {"rate": rate})

    def record_replay(self, elapsed_s: float, streams: int,
                      frames: int, paced: bool = False,
                      paced_streams: int = 0,
                      paced_frames: int = 0) -> None:
        """One failover/drain migration wave (_migrate_streams), or a
        deferred paced-recovery wave: a global gateway-lane span so
        recovery storms are visible on the merged fleet timeline.
        `streams`/`frames` count what THIS wave replayed;
        `paced_streams`/`paced_frames` count what it re-pinned but
        deferred to scheduled `paced_replay:` waves."""
        if self.enabled:
            name = "paced_replay:gateway" if paced else "replay:gateway"
            args = {"streams": streams, "frames": frames}
            if paced_streams:
                args["paced_streams"] = paced_streams
                args["paced_frames"] = paced_frames
            self.tracer.span_global(name, "gateway", elapsed_s, args)

    # -- per-stream end-to-end decomposition -------------------------------

    def record_stage(self, stream_id: str, stage: str,
                     elapsed_s: float) -> None:
        """Accumulate one stage's share of a stream's end-to-end
        latency.  Stages: admit (admission processing), route
        (placement decisions), queue (parked wait), prefill (disagg
        hop 1), decode (pinned-replica service), emit (response
        delivery)."""
        if not self.enabled:
            return
        stages = self._decomposition.get(stream_id)
        if stages is None:
            if len(self._decomposition) >= DECOMPOSITION_STREAM_CAP:
                # the map is a compact view, not a database: past the
                # cap a stream's stages fold straight into the
                # persistent fleet total (same place destroyed streams
                # land), keeping memory and publish cost bounded at
                # 10k-stream scale
                self._decomposition_total[stage] = (
                    self._decomposition_total.get(stage, 0.0)
                    + elapsed_s)
                return
            stages = self._decomposition[stream_id] = {}
        stages[stage] = stages.get(stage, 0.0) + elapsed_s

    def forget_stream(self, stream_id: str) -> None:
        stages = self._decomposition.pop(stream_id, None)
        if stages:
            for stage, seconds in stages.items():
                self._decomposition_total[stage] = (
                    self._decomposition_total.get(stage, 0.0) + seconds)

    def stream_decomposition(self) -> dict:
        """Per-LIVE-stream decomposition in ms (bounded by
        DECOMPOSITION_STREAM_CAP; overflow streams accumulate straight
        into the total) plus the fleet `_total` aggregate (destroyed
        streams included) -- where every admitted stream's latency
        went, end to end."""
        totals = dict(self._decomposition_total)
        rendered = {}
        for stream_id in sorted(self._decomposition):
            stages = self._decomposition[stream_id]
            for stage, seconds in stages.items():
                totals[stage] = totals.get(stage, 0.0) + seconds
            rendered[stream_id] = {
                stage: round(seconds * 1e3, 3)
                for stage, seconds in sorted(stages.items())}
        rendered["_total"] = {stage: round(seconds * 1e3, 3)
                              for stage, seconds in sorted(
                                  totals.items())}
        return rendered

    # -- per-priority SLO attainment ---------------------------------------

    def record_slo(self, priority: int, within: bool,
                   tenant: str | None = None) -> None:
        """One completed frame of an SLO-carrying stream judged against
        its declared slo_ms: per-priority-bucket attainment/burn
        counters, plus a parallel per-TENANT family (`:t:{tenant}`)
        when the stream declared one -- the per-tenant accounting
        surface the multi-tenant isolation test reads."""
        if not self.enabled:
            return
        kind = "slo_ok" if within else "slo_miss"
        self.registry.counter(f"gateway.{kind}:p{priority}").inc()
        if tenant:
            self.registry.counter(f"gateway.{kind}:t:{tenant}").inc()

    def configure_slo_window(self, window_s: float) -> None:
        """Re-window the burn accounting (the autopilot aligns it with
        its policy's burn_window).  Existing samples are discarded --
        a window change is a new measurement, not a rescale."""
        self.slo_window = SlidingWindow(max(float(window_s), 1e-9))

    def sample_slo_window(self, now: float | None = None) -> None:
        """Feed the cumulative slo_ok/slo_miss counters into the
        sliding window.  Called from the snapshot timer and from the
        autopilot immediately before it reads the gate, so the window
        is fresh at decision time."""
        values = {name: counter.value
                  for name, counter in list(
                      self.registry._counters.items())
                  if name.startswith(("gateway.slo_ok:p",
                                      "gateway.slo_miss:p"))}
        self.slo_window.sample(monotonic() if now is None else now,
                               values)

    def windowed_burn(self, priority=None) -> float | None:
        """Burn rate miss/(ok+miss) over the sliding window -- across
        ALL priorities by default, or one priority bucket.  None when
        the window saw no judged traffic (no signal != zero burn)."""
        if priority is not None:
            return self.slo_window.burn(
                f"gateway.slo_miss:p{priority}",
                f"gateway.slo_ok:p{priority}")
        ok = miss = 0.0
        if len(self.slo_window._samples) < 2:
            return None
        for name in self.slo_window._samples[-1][2]:
            if name.startswith("gateway.slo_miss:p"):
                miss += self.slo_window.delta(name)
            elif name.startswith("gateway.slo_ok:p"):
                ok += self.slo_window.delta(name)
        total = ok + miss
        if total <= 0:
            return None
        return miss / total

    def slo_summary(self) -> dict:
        """Per-priority {ok, miss, attainment, burn, burn_window}:
        attainment is the in-SLO fraction, burn its cumulative
        complement (the error-budget burn fraction), burn_window the
        SAME ratio over the sliding window only (absent when the
        window saw no judged traffic)."""
        buckets: dict[str, dict] = {}
        snapshot = self.registry.snapshot()
        for name, value in (snapshot.get("counters") or {}).items():
            for kind, prefix in (("ok", "gateway.slo_ok:p"),
                                 ("miss", "gateway.slo_miss:p")):
                if name.startswith(prefix):
                    priority = name[len(prefix):]
                    buckets.setdefault(priority, {"ok": 0, "miss": 0})[
                        kind] = int(value)
        for priority, record in buckets.items():
            judged = record["ok"] + record["miss"]
            record["attainment"] = round(
                record["ok"] / judged, 4) if judged else None
            record["burn"] = round(
                record["miss"] / judged, 4) if judged else None
            windowed = self.windowed_burn(priority)
            if windowed is not None:
                record["burn_window"] = round(windowed, 4)
        # numeric priority order (p2 before p10), odd keys last
        return dict(sorted(
            buckets.items(),
            key=lambda item: (not item[0].isdigit(),
                              int(item[0]) if item[0].isdigit() else 0,
                              item[0])))

    def record_queue_depths(self, depths: dict) -> None:
        """Parked-queue occupancy PER PRIORITY (gauge family
        `gateway.queue_depth:p{n}`): overload triage needs to see WHICH
        priorities are waiting, not only the total."""
        if not self.enabled:
            return
        for priority, depth in depths.items():
            self.registry.gauge(
                f"gateway.queue_depth:p{priority}").set(depth)

    def record_replica_routed(self, replica_name: str) -> None:
        if not self.enabled:
            return
        self.registry.counter(f"gateway.routed:{replica_name}").inc()

    def record_spawn(self, time_to_healthy_ms: float,
                     warm: bool) -> None:
        """One finished replica bring-up: decision -> healthy, labeled
        warm (sibling hand-off + compile-cache) or cold."""
        self.time_to_healthy.record(time_to_healthy_ms)
        self.last_time_to_healthy_ms = round(time_to_healthy_ms, 2)
        (self.warm_spawns if warm else self.cold_spawns).inc()

    def record_takeover(self, takeover_ms: float) -> None:
        """One HA takeover: standby promoted, journal adopted, streams
        re-pinned."""
        self.takeovers.inc()
        self.takeover_ms.record(takeover_ms)
        self.last_takeover_ms = round(takeover_ms, 2)

    def snapshot(self) -> dict:
        return self.registry.snapshot()

    def summary(self) -> dict:
        """Compact scalars for the EC share / dashboards.  Admit-latency
        quantiles come from the ONE shared Histogram.quantile helper
        (the same estimate `aiko tune` and the dashboard read) instead
        of an ad-hoc re-derivation."""
        summary = {
            "admitted": self.admitted.value,
            "shed_streams": self.shed_streams.value,
            "shed_frames": self.shed_frames.value,
            "routed": self.routed.value,
            "completed": self.completed.value,
            "released": self.released.value,
            "throttled": self.throttled.value,
            "failovers": self.failovers.value,
            "replica_deaths": self.replica_deaths.value,
            "replicas": self.replicas.value,
            "parked": self.parked.value,
            "pool_size": self.pool_size.value,
            "scale_ups": self.scale_ups.value,
            "scale_downs": self.scale_downs.value,
        }
        if self.prefill_routed.value:
            summary["prefill_routed"] = self.prefill_routed.value
            summary["kv_migrations"] = self.kv_migrations.value
            summary["prefill_fallbacks"] = self.prefill_fallbacks.value
        if self.recovery_paced.value:
            summary["recovery_paced"] = self.recovery_paced.value
        if self.affinity_hits.value or self.affinity_misses.value:
            summary["affinity_hits"] = self.affinity_hits.value
            summary["affinity_misses"] = self.affinity_misses.value
        if self.region_migrations.value:
            summary["region_migrations"] = self.region_migrations.value
        if (self.region_affinity_hits.value
                or self.region_affinity_misses.value):
            summary["region_affinity_hits"] = (
                self.region_affinity_hits.value)
            summary["region_affinity_misses"] = (
                self.region_affinity_misses.value)
        slo = self.slo_summary()
        if slo:
            # per-priority SLO attainment/burn (the per-tenant
            # accounting surface): only streams that DECLARED slo_ms
            # are judged, so the key is absent on SLO-less fleets
            summary["slo"] = slo
        if self._decomposition or self._decomposition_total:
            summary["stream_decomposition"] = (
                self.stream_decomposition())
        if self.latency.count:
            summary["admit_latency_p50_ms"] = round(
                self.latency.quantile(0.5) * 1000, 3)
            summary["admit_latency_p99_ms"] = round(
                self.latency.quantile(0.99) * 1000, 3)
        if self.last_time_to_healthy_ms is not None:
            summary["time_to_healthy_ms"] = self.last_time_to_healthy_ms
        autoscaler = getattr(self.gateway, "autoscaler", None)
        if autoscaler is not None:
            summary["pool"] = self.gateway.pool_snapshot()
            summary["pending_spawns"] = autoscaler.pending
        journal = getattr(self.gateway, "journal", None)
        if journal is not None:
            ha = {
                "role": getattr(self.gateway, "role", "single"),
                "backend": journal.backend.kind,
                "journal_entries": self.journal_entries.value,
                "journal_appends": self.journal_appends.value,
                "replayed": self.journal_replayed.value,
                "dropped_stale": self.journal_dropped_stale.value,
                "takeovers": self.takeovers.value,
            }
            if self.last_takeover_ms is not None:
                ha["takeover_ms"] = self.last_takeover_ms
            summary["ha"] = ha
        if self.autopilot_summary is not None:
            summary["autopilot"] = self.autopilot_summary
        return summary

    def _publish_snapshot(self) -> None:
        gateway = self.gateway
        try:
            self.sample_slo_window()
            from ..utils import generate
            gateway.process.publish(
                f"{gateway.topic_path}/metrics",
                generate("metrics",
                         [gateway.topic_path, self.snapshot()]))
            if gateway.ec_producer is not None:
                # staged: the summary mirror coalesces with any
                # stream-churn share updates pending this tick
                gateway.ec_producer.stage("metrics", self.summary())
        except Exception as error:  # export must never kill the gateway
            _LOGGER.warning("gateway metrics publish failed: %s", error)

    # -- trace export (PipelineTelemetry-compatible surface) ---------------

    def chrome_events(self) -> list:
        return self.tracer.chrome_events(
            process_name=f"gateway:{self.gateway.name}")

    def trace_metadata(self, config: dict | None = None,
                       config_name: str | None = None) -> dict:
        """Self-describing metadata for the gateway's trace artifact:
        no pipeline definition (a gateway runs no graph), but the
        metrics snapshot, the tracer pid, and -- like every process --
        the clock epoch the fleet merger aligns with."""
        metadata = trace_metadata(config=config,
                                  config_name=config_name,
                                  metrics=self.snapshot(),
                                  clock_epoch=True)
        metadata["pids"] = [self.tracer._pid]
        metadata["role"] = "gateway"
        return metadata

    def export_trace(self, path: str, config: dict | None = None,
                     config_name: str | None = None) -> int:
        return self.tracer.export(
            path, process_name=f"gateway:{self.gateway.name}",
            metadata=self.trace_metadata(config=config,
                                         config_name=config_name))

    def stop(self) -> None:
        if self._timer is not None:
            self.gateway.process.event.remove_timer_handler(self._timer)
            self._timer = None
            self._publish_snapshot()
