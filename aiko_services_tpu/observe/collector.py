# Fleet trace collector + merger: many per-process Perfetto artifacts
# -> ONE clock-aligned, parent-linked timeline.
#
# Every process exports its own artifact (bench.py --trace per-config
# files, `pipeline.telemetry.export_trace`, the gateway's
# `telemetry.export_trace`, or the live `(publish_trace ...)` wire
# query), each timed against its OWN perf_counter epoch.  Merging has
# two jobs:
#
#   clock calibration   every artifact's metadata records
#                       `clock_epoch_unix_us` -- the wall-clock moment
#                       of that process's trace timestamp 0
#                       (observe/trace.clock_epoch_unix_us).  The
#                       merger picks the EARLIEST epoch as the shared
#                       reference and shifts every other artifact's
#                       timestamps by (epoch - reference), so spans
#                       concurrent in wall time stay concurrent on the
#                       merged timeline.  Artifacts without an epoch
#                       (pre-fleet traces, foreign tools) merge at
#                       offset 0 with a diagnostic note.
#   identity            synthetic tracer pids are unique per process
#                       but CAN collide across hosts; colliding pids
#                       are deterministically remapped.  Cross-process
#                       parenting needs no rewriting: frame spans carry
#                       their own `span_id` and the propagated
#                       `parent` span id in args (observe/trace.py
#                       taxonomy), both stable under the merge.
#
# Determinism contract: merging the same inputs in the same order is
# BYTE-identical (sorted events, sorted JSON keys, no timestamps or
# environment reads beyond the artifacts themselves), so CI diffs two
# merges of one bench run to prove it.

from __future__ import annotations

import json

from .metrics import merge_snapshots
from .trace import TRACE_METADATA_SCHEMA, trace_metadata_of

__all__ = ["collect_traces", "merge_trace_documents",
           "merge_trace_files", "publish_trace_document",
           "trace_summary", "unique_source_name"]


def unique_source_name(seen: dict, name: str) -> str:
    """Deterministic collision suffixing for merge-source names: two
    distinct sources flattening to one name (same artifact basename on
    two hosts, topic paths collapsing under '/'->'_') must not
    overwrite each other's file or `merged` provenance record.  `seen`
    is the caller's running {name: count} map."""
    count = seen.get(name, 0)
    seen[name] = count + 1
    return f"{name}~{count}" if count else name


def publish_trace_document(process, telemetry, topic_path: str,
                           topic_response) -> None:
    """THE `(publish_trace ...)` reply shape, shared by Pipeline and
    Gateway: publish the actor's self-describing Perfetto document as
    `(trace <topic_path> <json-document>)` on `topic_response`.  JSON
    text, not a nested sexpr tree -- the wire codec would stringify
    every number and the merger needs exact types."""
    from ..utils import generate
    from .trace import chrome_trace_document
    document = chrome_trace_document(
        telemetry.chrome_events(), metadata=telemetry.trace_metadata())
    process.publish(
        str(topic_response),
        generate("trace", [topic_path,
                           json.dumps(document).encode("ascii")]))


def _doc_epoch(metadata: dict | None) -> float | None:
    if not isinstance(metadata, dict):
        return None
    epoch = metadata.get("clock_epoch_unix_us")
    if isinstance(epoch, (int, float)):
        return float(epoch)
    # combined legacy artifacts keep per-run metadata under "runs":
    # the earliest run epoch stands in for the document
    runs = metadata.get("runs")
    if isinstance(runs, dict):
        epochs = [run.get("clock_epoch_unix_us")
                  for run in runs.values() if isinstance(run, dict)]
        epochs = [float(value) for value in epochs
                  if isinstance(value, (int, float))]
        if epochs:
            return min(epochs)
    return None


def _doc_pids(document: dict) -> list:
    pids = set()
    for event in document.get("traceEvents") or []:
        if isinstance(event, dict) and isinstance(
                event.get("pid"), int):
            pids.add(event["pid"])
    return sorted(pids)


def _event_sort_key(event: dict) -> tuple:
    args = event.get("args")
    return (
        0 if event.get("ph") == "M" else 1,
        float(event.get("ts", 0.0) or 0.0),
        int(event.get("pid", 0) or 0),
        int(event.get("tid", 0) or 0),
        str(event.get("name", "")),
        json.dumps(args, sort_keys=True, default=str)
        if args is not None else "",
    )


def merge_trace_documents(named_documents: list) -> dict:
    """[(source_name, chrome_trace_document), ...] -> ONE merged
    document.  Callers pass inputs in a stable order (the CLI sorts
    file paths); the output is then byte-deterministic."""
    reference = None
    prepared = []
    for name, document in named_documents:
        if not isinstance(document, dict) or not isinstance(
                document.get("traceEvents"), list):
            raise ValueError(
                f"{name}: not a Chrome-trace document "
                f"(no traceEvents list)")
        metadata = trace_metadata_of(document)
        epoch = _doc_epoch(metadata)
        prepared.append((str(name), document, metadata, epoch))
        if epoch is not None:
            reference = epoch if reference is None \
                else min(reference, epoch)
    if reference is None:
        reference = 0.0

    used_pids: set = set()
    merged_events: list = []
    merged_sources: dict = {}
    merged_metrics: dict = {}
    definition = None
    fingerprint = ""
    config = None
    config_name = ""
    unaligned = []
    collisions: dict = {}
    all_pids: set = set()
    for name, document, metadata, epoch in prepared:
        offset_us = (epoch - reference) if epoch is not None else 0.0
        if epoch is None:
            unaligned.append(name)
        pid_map: dict = {}
        # trace/span ids embed the minting tracer's pid
        # ({pid:x}-{seq:x} / {pid:x}.{seq:x}), so a remapped pid must
        # also rewrite THIS document's OWN id strings or two unrelated
        # hosts with colliding pids would read as one trace.  Only ids
        # this document minted are rewritten: every span_id (frame
        # spans mint their own), and trace_ids of traces ROOTED here
        # (no `parent` on the frame span).  An ADOPTED trace's id and
        # every `parent` were minted upstream -- rewriting those would
        # sever the cross-process links this merger exists to keep
        # (the propagating process keeps the original strings).  A
        # reference REACHING a remapped document from another document
        # is inherently ambiguous (the same string names the
        # un-remapped twin too), so the collision is flagged in
        # metadata instead of guessed at
        id_rewrites: dict = {}
        for pid in _doc_pids(document):
            if pid in used_pids:
                fresh = max(used_pids) + 1
                while fresh in used_pids:
                    fresh += 1
                pid_map[pid] = fresh
                used_pids.add(fresh)
                id_rewrites[f"{pid:x}"] = f"{fresh:x}"
                collisions.setdefault(pid, []).append(str(name))
            else:
                pid_map[pid] = pid
                used_pids.add(pid)
        all_pids.update(pid_map.values())
        foreign_traces: set = set()
        if id_rewrites:
            # trace ids carried by an adopted (parented) frame span
            # were minted by the UPSTREAM process: every event of that
            # trace keeps the foreign id
            for event in document.get("traceEvents") or []:
                if not isinstance(event, dict) \
                        or event.get("cat") != "frame":
                    continue
                args = event.get("args")
                if isinstance(args, dict) and args.get("parent") \
                        and args.get("trace_id"):
                    foreign_traces.add(str(args["trace_id"]))
        for event in document.get("traceEvents") or []:
            if not isinstance(event, dict):
                continue
            rewritten = dict(event)
            pid = rewritten.get("pid")
            if isinstance(pid, int) and pid in pid_map:
                rewritten["pid"] = pid_map[pid]
            ts = rewritten.get("ts")
            if isinstance(ts, (int, float)):
                rewritten["ts"] = round(float(ts) + offset_us, 3)
            args = rewritten.get("args")
            if id_rewrites and isinstance(args, dict) and args:
                patched = None
                for key, separator in (("trace_id", "-"),
                                       ("span_id", ".")):
                    value = args.get(key)
                    if not isinstance(value, str) \
                            or separator not in value:
                        continue
                    if key == "trace_id" and value in foreign_traces:
                        continue
                    prefix, rest = value.split(separator, 1)
                    fresh_hex = id_rewrites.get(prefix)
                    if fresh_hex is None:
                        continue
                    if patched is None:
                        patched = dict(args)
                    patched[key] = f"{fresh_hex}{separator}{rest}"
                if patched is not None:
                    rewritten["args"] = patched
            merged_events.append(rewritten)
        source: dict = {
            "offset_us": round(offset_us, 3),
            "pids": sorted(pid_map.values()),
        }
        if epoch is not None:
            source["clock_epoch_unix_us"] = round(epoch, 3)
        if isinstance(metadata, dict):
            if metadata.get("role"):
                source["role"] = metadata["role"]
            if metadata.get("config_name"):
                source["config_name"] = metadata["config_name"]
            metrics = metadata.get("metrics")
            if isinstance(metrics, dict):
                merged_metrics = merge_snapshots(merged_metrics,
                                                 metrics)
            if definition is None and isinstance(
                    metadata.get("definition"), dict):
                # the first (in caller order) definition-carrying
                # artifact donates the graph the tune loader joins
                # element spans against; gateway artifacts carry none
                definition = metadata["definition"]
                fingerprint = metadata.get("fingerprint") or ""
                config = metadata.get("config")
                config_name = metadata.get("config_name") or ""
        merged_sources[str(name)] = source

    merged_events.sort(key=_event_sort_key)
    metadata: dict = {
        "schema": TRACE_METADATA_SCHEMA,
        "clock_epoch_unix_us": round(reference, 3),
        "merged": merged_sources,
        "pids": sorted(all_pids),
    }
    if definition is not None:
        metadata["definition"] = definition
        if fingerprint:
            metadata["fingerprint"] = fingerprint
    if config is not None:
        metadata["config"] = config
    if config_name:
        metadata["config_name"] = config_name
    if merged_metrics:
        metadata["metrics"] = merged_metrics
    if unaligned:
        metadata["unaligned_sources"] = sorted(unaligned)
    if collisions:
        # cross-document references into a remapped source cannot be
        # disambiguated (the colliding twin owns the same id strings):
        # consumers must treat parent links touching these pids as
        # unreliable
        metadata["pid_collisions"] = {
            str(pid): sorted(names)
            for pid, names in sorted(collisions.items())}
    return {"traceEvents": merged_events, "displayTimeUnit": "ms",
            "metadata": {"aiko": metadata}}


def merge_trace_files(paths: list, output: str | None = None) -> dict:
    """Merge trace artifacts from disk (inputs sorted by basename then
    path, so the SAME file set always merges byte-identically) and
    optionally write the merged document with sorted keys."""
    import os
    ordered = sorted(paths, key=lambda path: (os.path.basename(path),
                                              path))
    named = []
    seen: dict = {}
    for path in ordered:
        name = unique_source_name(seen, os.path.basename(path))
        with open(path) as handle:
            named.append((name, json.load(handle)))
    merged = merge_trace_documents(named)
    if output:
        with open(output, "w") as handle:
            json.dump(merged, handle, sort_keys=True,
                      separators=(",", ":"))
    return merged


def trace_summary(document: dict) -> dict:
    """Quick shape check of a (merged) artifact: per-trace-id process
    counts and cross-process link integrity -- what the CI trace step
    asserts instead of eyeballing Perfetto."""
    span_ids = set()
    links = []            # (child label, parent span id)
    trace_pids: dict = {}  # trace_id -> set of pids
    categories: dict = {}
    last_end_us = 0.0
    for event in document.get("traceEvents") or []:
        if not isinstance(event, dict) or event.get("ph") not in (
                "X", "i"):
            continue
        category = str(event.get("cat", ""))
        categories[category] = categories.get(category, 0) + 1
        ts = float(event.get("ts", 0.0) or 0.0)
        last_end_us = max(last_end_us,
                          ts + float(event.get("dur", 0.0) or 0.0))
        args = event.get("args") or {}
        trace_id = args.get("trace_id")
        if trace_id:
            trace_pids.setdefault(str(trace_id), set()).add(
                event.get("pid"))
        span_id = args.get("span_id")
        if span_id:
            span_ids.add(str(span_id))
        parent = args.get("parent")
        if parent:
            # spans without their own span_id (adopt spans) still
            # carry cross-process parent links -- label them by name
            # so a broken link never hides from dangling_parents
            child = (str(span_id) if span_id
                     else f"{event.get('name', '')}@{ts}")
            links.append((child, str(parent)))
    max_processes = max((len(pids) for pids in trace_pids.values()),
                        default=0)
    dangling = sorted({child for child, parent in links
                       if parent not in span_ids})
    return {
        "traces": len(trace_pids),
        "max_processes_per_trace": max_processes,
        "multi_process_traces": sum(
            1 for pids in trace_pids.values() if len(pids) >= 2),
        "linked_spans": len(links),
        "dangling_parents": dangling,
        "categories": dict(sorted(categories.items())),
        "span_end_max_us": round(last_end_us, 3),
    }


def collect_traces(process, wait: float = 3.0,
                   protocols: tuple = ("pipeline", "gateway"),
                   targets=None) -> dict:
    """Harvest live per-process trace documents over the control
    plane: discover every pipeline/gateway service through the shared
    ServicesCache (or query the explicit `targets` topic paths and
    skip discovery), send each `(publish_trace <response_topic>)`,
    and gather the `(trace <source> <document>)` replies.  Returns
    {source_topic_path: document} -- feed `.items()` (sorted) to
    merge_trace_documents.

    `wait` is a DEADLINE, not a sleep: once every queried service has
    replied the collector returns immediately (a healthy fleet pays
    round-trip latency, not the timeout).  `collector.responses` /
    `collector.timeouts` counters in the process-global registry make
    partial harvests visible instead of silent."""
    import threading

    from ..utils import generate, parse
    from .metrics import get_registry

    response_topic = f"{process.topic_path_process}/trace_collect"
    collected: dict = {}
    lock = threading.Lock()
    registry = get_registry()

    def on_trace(topic, payload):
        try:
            command, parameters = parse(payload)
        except ValueError:
            return
        if command != "trace" or len(parameters) < 2:
            return
        source, document = str(parameters[0]), parameters[1]
        if isinstance(document, (str, bytes)):
            # documents travel as JSON text (exact numeric types)
            try:
                document = json.loads(document)
            except ValueError:
                return
        if isinstance(document, dict):
            with lock:
                if source not in collected:
                    registry.counter("collector.responses").inc()
                collected[source] = document

    process.add_message_handler(on_trace, response_topic)
    queried: set = set()
    handlers = []
    cache = None
    if targets is not None:
        for topic_path in targets:
            topic_path = str(topic_path)
            if topic_path not in queried:
                queried.add(topic_path)
                process.publish(
                    f"{topic_path}/in",
                    generate("publish_trace", [response_topic]))
    else:
        from ..runtime import ServiceFilter
        from ..runtime.service import SERVICE_PROTOCOL_PIPELINE
        from ..runtime.share import services_cache_create_singleton
        from ..serve import SERVICE_PROTOCOL_GATEWAY
        wanted = {
            "pipeline": SERVICE_PROTOCOL_PIPELINE,
            "gateway": SERVICE_PROTOCOL_GATEWAY,
        }
        cache = services_cache_create_singleton(process)

        def handler(command, fields):
            if command == "add" and fields.topic_path not in queried:
                queried.add(fields.topic_path)
                process.publish(
                    f"{fields.topic_path}/in",
                    generate("publish_trace", [response_topic]))

        for kind in protocols:
            protocol = wanted.get(kind)
            if protocol is None:
                continue
            service_filter = ServiceFilter(protocol=protocol)
            cache.add_handler(handler, service_filter)
            handlers.append((handler, service_filter))
    import time as _time
    start = _time.monotonic()
    deadline = start + max(wait, 0.0)
    # early return needs a CLOSED respondent set: explicit targets are
    # closed by construction; under discovery the set only grows, so a
    # short grace keeps a service registering right behind the first
    # batch from being cut off before it is even queried
    grace = 0.0 if targets is not None else min(max(wait, 0.0), 0.5)
    while _time.monotonic() < deadline:
        with lock:
            answered = len(collected)
        expected = len(queried)
        if expected and answered >= expected \
                and _time.monotonic() - start >= grace:
            break
        _time.sleep(0.01)
    for added, _filter in handlers:
        try:
            cache.remove_handler(added)
        except Exception:
            pass
    process.remove_message_handler(on_trace, response_topic)
    with lock:
        missing = len(queried) - len(collected)
        if missing > 0:
            registry.counter("collector.timeouts").inc(missing)
        return dict(collected)
