# observe/: pipeline telemetry -- metrics registry, frame tracing, and
# live export over the control plane (see ISSUE 2 / README
# "Observability").  Layerless by design: metrics.py and trace.py are
# stdlib-only so any layer (transport, transfer plane, elements) can
# record without import cycles; telemetry.py is the pipeline engine's
# glue and the only module that knows what a Pipeline is.

from .metrics import (                                      # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry, SlidingWindow,
    get_registry, merge_snapshots, snapshot_from_wire,
    snapshot_quantile)
from .trace import (                                        # noqa: F401
    FrameTrace, TRACE_CONTEXT_KEY, Tracer, attach_trace_context,
    chrome_trace_document, clock_epoch_unix_us,
    definition_fingerprint, make_trace_context, pop_trace_context,
    trace_context_of, trace_metadata, trace_metadata_of)
from .collector import (                                    # noqa: F401
    collect_traces, merge_trace_documents, merge_trace_files,
    publish_trace_document, trace_summary)
from .telemetry import PipelineTelemetry                    # noqa: F401
from .gateway import GatewayTelemetry                       # noqa: F401

# NOTE: `Tracer.span_global` (global-lane duration spans -- work
# belonging to no single frame, e.g. decode-state checkpoints) and the
# span taxonomy itself are documented ONCE, in observe/trace.py's
# module docstring; every producer and the tune loader follow it.
