# observe/: pipeline telemetry -- metrics registry, frame tracing, and
# live export over the control plane (see ISSUE 2 / README
# "Observability").  Layerless by design: metrics.py and trace.py are
# stdlib-only so any layer (transport, transfer plane, elements) can
# record without import cycles; telemetry.py is the pipeline engine's
# glue and the only module that knows what a Pipeline is.

from .metrics import (                                      # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry, get_registry,
    merge_snapshots, snapshot_from_wire, snapshot_quantile)
from .trace import (                                        # noqa: F401
    FrameTrace, Tracer, chrome_trace_document,
    definition_fingerprint, trace_metadata, trace_metadata_of)
from .telemetry import PipelineTelemetry                    # noqa: F401
from .gateway import GatewayTelemetry                       # noqa: F401
