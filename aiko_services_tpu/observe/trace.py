# Frame tracer: Dapper-style per-frame traces exported as Chrome-trace
# JSON (Perfetto-loadable).
#
# A trace id is minted per frame at stream ingress; the pipeline engine
# appends span records (element execution, queue wait, fused vs chained
# dispatch, park/resume, compile events) to the frame's FrameTrace as the
# frame moves through the graph.  Completed traces land in a bounded ring;
# export renders them as Chrome trace-event JSON ("X" complete events for
# spans, "i" instants for point events, "M" metadata naming the process
# and one thread lane per stream), which chrome://tracing and Perfetto
# both load directly.
#
# SPAN TAXONOMY -- the one canonical reference (telemetry.py, the
# serving gateway, and tune/loader.py all follow this table):
#
#   category   span names                    meaning
#   --------   ---------------------------   ---------------------------
#   frame      "frame {id}"                  one frame's whole lifetime
#                                            in ONE process (per-process
#                                            root; carries span_id and,
#                                            on a propagated trace, the
#                                            upstream parent span id)
#   element    "{node}"                      one element call; args.path
#                                            = inline|fused|chained|
#                                            async|remote
#   queue      "queue:{node}"                scheduler-induced wait
#                                            (micro-batch park -> flush,
#                                            or engine slot wait when
#                                            row-suffixed "queue:lm[3]")
#   engine     "prefill:{node}",             continuous-batching engine
#              "decode_steps:{node}",        phases; "adopt:" = KV
#              "adopt:{node}",               migration (disagg or warm
#              "checkpoint:{node}"           restore), "checkpoint:" =
#                                            snapshot shipping (global
#                                            lane: covers every slot)
#   gateway    "admit:gateway",              serving-tier spans: admit =
#              "route:gateway",              frame submit -> replica
#              "replay:gateway",             dispatch (parked/admission
#              "shed:gateway",               wait), route = placement
#              "throttle:gateway",           decision, replay = failover
#              "paced_replay:gateway"        _migrate_streams wave,
#                                            paced_replay = deferred
#                                            recovery wave; shed/
#                                            throttle are instants
#   compile    "compile:{node}"              (re)compilation instants
#   park/fault instants                      park/resume, retries,
#                                            deadline + breaker events
#
# Naming scheme: "{kind}:{node}" -- tune/loader._node_of strips the
# prefix (and the "[row]" suffix) to join spans to typed graph nodes.
# The matching frame.metrics keys split the SAME way on every dispatch
# path: `time_{node}` is element/device compute, `time_queue_{node}` is
# scheduler wait (micro-batch fill, engine slot wait) -- never mixed.
#
# Cross-process propagation: a TRACE CONTEXT ({trace_id, span_id}) rides
# frame data under TRACE_CONTEXT_KEY.  The serving gateway mints the
# trace at admission (root-span owner); every downstream process pops
# the context at stream ingress and CONTINUES the same trace -- its
# frame span carries the propagated trace_id plus parent = the upstream
# span id, so a merged artifact (observe/collector.py) nests gateway ->
# replica -> prefill/keeper spans on one timeline.
#
# Cost contract: when tracing is disabled the frame carries trace=None
# and every hook is a single `is None` check; when enabled, a span is one
# perf_counter read and one tuple append -- no dict churn on the hot
# path, events materialize only at export.

from __future__ import annotations

import hashlib
import itertools
import json
import os
import time
from collections import deque

__all__ = ["FrameTrace", "Tracer", "TRACE_CONTEXT_KEY",
           "attach_trace_context", "chrome_trace_document",
           "clock_epoch_unix_us", "definition_fingerprint",
           "make_trace_context", "pop_trace_context", "trace_context_of",
           "trace_metadata", "trace_metadata_of"]

# trace-metadata schema version: bumped when the embedded layout
# changes; the tune/ loader refuses versions it does not understand
# instead of silently mis-reading spans
TRACE_METADATA_SCHEMA = 1

# One clock epoch per process: every span timestamp is microseconds since
# this moment, so spans from different streams/elements line up on one
# export timeline.
_EPOCH = time.perf_counter()


def now_us() -> float:
    return (time.perf_counter() - _EPOCH) * 1e6


def to_us(perf_counter_s: float) -> float:
    """A raw time.perf_counter() reading on the export timeline."""
    return (perf_counter_s - _EPOCH) * 1e6


def clock_epoch_unix_us() -> float:
    """Wall-clock microseconds (Unix epoch) at THIS process's trace
    timestamp 0.  Every export stamps it into trace_metadata so the
    fleet merger (observe/collector.py) can shift per-process
    timestamps onto one shared timeline: two processes whose spans are
    concurrent in wall time stay concurrent in the merged artifact,
    regardless of when each process booted."""
    return time.time() * 1e6 - now_us()


# reserved frame-data key the cross-process trace context rides under:
# popped at stream ingress (never reaches element inputs), absent
# entirely when the sender's telemetry is disabled -- the wire payload
# is then byte-identical to an untraced build's
TRACE_CONTEXT_KEY = "_trace_context"


def make_trace_context(trace: "FrameTrace") -> dict:
    """The propagable identity of one frame trace: the (possibly
    already-propagated) trace id plus THIS process's frame span id as
    the downstream parent."""
    return {"trace_id": trace.trace_id, "span_id": trace.span_id}


def trace_context_of(frame_data) -> dict | None:
    """Read (without removing) the trace context riding `frame_data`."""
    if not isinstance(frame_data, dict):
        return None
    context = frame_data.get(TRACE_CONTEXT_KEY)
    return context if isinstance(context, dict) else None


def attach_trace_context(frame_data: dict, context: dict) -> dict:
    """A COPY of `frame_data` carrying `context` -- the original stays
    untouched so failover replay / byte-compare semantics hold."""
    merged = dict(frame_data)
    merged[TRACE_CONTEXT_KEY] = context
    return merged


def pop_trace_context(frame_data) -> dict | None:
    """Remove and return the trace context (stream-ingress side): the
    context must never leak into element inputs or outputs."""
    if not isinstance(frame_data, dict):
        return None
    context = frame_data.pop(TRACE_CONTEXT_KEY, None)
    return context if isinstance(context, dict) else None


class FrameTrace:
    """Span accumulator for ONE frame: rides Frame.trace through the
    graph.  `marks` holds open interval starts (queue parks) keyed by
    node; `events` holds finished records as tuples
    (kind, name, category, ts_us, dur_us, args).  The frame's own
    top-level span is NOT an event -- it is built at export from
    start/end/status, keeping the per-frame hot path to appends."""

    __slots__ = ("pid", "seq", "stream_id", "frame_id", "start_us",
                 "end_us", "status", "events", "marks",
                 "origin_trace_id", "parent_span_id")

    def __init__(self, pid: int, seq: int, stream_id: str,
                 frame_id: int):
        self.pid = pid
        self.seq = seq
        self.stream_id = stream_id
        self.frame_id = frame_id
        self.start_us = now_us()
        self.end_us = None
        self.status = "ok"
        self.events: list = []
        self.marks: dict | None = None  # lazily built on first park
        # cross-process propagation (see TRACE_CONTEXT_KEY): when an
        # upstream process (the serving gateway) minted the trace, this
        # frame CONTINUES it -- same trace id, parented to the
        # upstream frame span
        self.origin_trace_id: str | None = None
        self.parent_span_id: str | None = None

    @property
    def trace_id(self) -> str:
        # formatted on demand: minting a frame costs no string build
        if self.origin_trace_id is not None:
            return self.origin_trace_id
        return f"{self.pid:x}-{self.seq:x}"

    @property
    def span_id(self) -> str:
        """This frame span's own identity -- what downstream processes
        record as their parent.  (pid, seq) is unique per tracer and
        pids are synthetic-per-process, so ids survive a fleet merge."""
        return f"{self.pid:x}.{self.seq:x}"

    def adopt(self, context: dict | None) -> None:
        """Continue a propagated trace: keep the upstream trace id and
        parent this process's frame span under the upstream span."""
        if not context:
            return
        trace_id = context.get("trace_id")
        if trace_id:
            self.origin_trace_id = str(trace_id)
        parent = context.get("span_id")
        if parent:
            self.parent_span_id = str(parent)

    def span(self, name: str, category: str, start_us: float,
             args: dict | None = None) -> None:
        self.events.append(("X", name, category, start_us,
                            now_us() - start_us, args))

    def instant(self, name: str, category: str,
                args: dict | None = None) -> None:
        self.events.append(("i", name, category, now_us(), 0.0, args))

    def mark(self, key: str) -> None:
        if self.marks is None:
            self.marks = {}
        self.marks[key] = now_us()

    def take_mark(self, key: str) -> float | None:
        if not self.marks:
            return None
        return self.marks.pop(key, None)


class Tracer:
    """Mints trace ids, keeps a bounded ring of completed frame traces,
    and renders Chrome-trace documents.  Global (non-frame) events --
    fused-program compiles, scheduler decisions -- accumulate in their
    own bounded list and export on a dedicated lane."""

    _pids = itertools.count()

    def __init__(self, ring_size: int = 256, pid: int | None = None):
        self._ids = itertools.count(1)
        # synthetic per-tracer pid: several pipelines' traces merged
        # into ONE file stay distinct processes in the Perfetto UI
        self._pid = (pid if pid is not None
                     else os.getpid() * 100 + next(Tracer._pids) % 100)
        self.completed: deque = deque(maxlen=ring_size)
        self.global_events: deque = deque(maxlen=1024)
        self._stream_lanes: dict[str, int] = {}
        # frames evicted from the bounded ring: exports surface this so
        # a truncated artifact never silently reads as full coverage
        self.dropped = 0

    def begin(self, stream_id: str, frame_id: int) -> FrameTrace:
        return FrameTrace(self._pid, next(self._ids), stream_id,
                          frame_id)

    def finish(self, trace: FrameTrace, status: str = "ok") -> None:
        trace.end_us = now_us()
        trace.status = status
        if len(self.completed) == self.completed.maxlen:
            self.dropped += 1
        self.completed.append(trace)

    def instant_global(self, name: str, category: str,
                       args: dict | None = None) -> None:
        self.global_events.append(("i", name, category, now_us(), 0.0,
                                   args))

    def span_global(self, name: str, category: str, elapsed_s: float,
                    args: dict | None = None) -> None:
        """A finished duration event on the global/scheduler lane --
        work that belongs to no single frame (a decode-state
        checkpoint covering every active slot).  Rendered as an X
        span ending now, so the tune loader can median it like any
        frame-attributed span."""
        self.global_events.append(
            ("X", name, category, now_us() - elapsed_s * 1e6,
             elapsed_s * 1e6, args))

    def _lane(self, stream_id: str) -> int:
        lane = self._stream_lanes.get(stream_id)
        if lane is None:
            lane = self._stream_lanes[stream_id] = (
                len(self._stream_lanes) + 1)
        return lane

    def chrome_events(self, process_name: str = "pipeline") -> list:
        """All completed traces + global events as Chrome trace-event
        dicts.  One pid per tracer, one tid lane per stream (lane 0 is
        the global/scheduler lane), metadata events name both."""
        events = [
            {"ph": "M", "name": "process_name", "pid": self._pid,
             "tid": 0, "args": {"name": process_name}},
            {"ph": "M", "name": "thread_name", "pid": self._pid,
             "tid": 0, "args": {"name": "scheduler"}},
        ]
        if self.dropped:
            events.append(self._event(
                "i", f"trace ring dropped {self.dropped} frames",
                "truncation", now_us(), 0.0,
                {"dropped_frames": self.dropped,
                 "ring_size": self.completed.maxlen}, tid=0))
        for kind, name, category, ts, dur, args in self.global_events:
            events.append(self._event(kind, name, category, ts, dur,
                                      args, tid=0))
        named_lanes = set()
        for trace in list(self.completed):
            lane = self._lane(trace.stream_id)
            if lane not in named_lanes:
                named_lanes.add(lane)
                events.append(
                    {"ph": "M", "name": "thread_name", "pid": self._pid,
                     "tid": lane,
                     "args": {"name": f"stream {trace.stream_id}"}})
            end_us = (trace.end_us if trace.end_us is not None
                      else now_us())
            frame_args = {"trace_id": trace.trace_id,
                          "span_id": trace.span_id,
                          "status": trace.status,
                          "stream": trace.stream_id}
            if trace.parent_span_id is not None:
                # propagated trace: this process's frame span nests
                # under the upstream (gateway) span in a merged artifact
                frame_args["parent"] = trace.parent_span_id
            events.append(self._event(
                "X", f"frame {trace.frame_id}", "frame", trace.start_us,
                end_us - trace.start_us, frame_args, tid=lane))
            for kind, name, category, ts, dur, args in trace.events:
                merged = {"trace_id": trace.trace_id,
                          "frame_id": trace.frame_id}
                if args:
                    merged.update(args)
                events.append(self._event(kind, name, category, ts, dur,
                                          merged, tid=lane))
        return events

    def _event(self, kind, name, category, ts, dur, args, tid) -> dict:
        event = {"ph": kind, "name": name, "cat": category,
                 "ts": round(ts, 3), "pid": self._pid, "tid": tid,
                 "args": args or {}}
        if kind == "X":
            event["dur"] = round(dur, 3)
        if kind == "i":
            event["s"] = "t"  # instant scope: thread
        return event

    def export(self, path: str, process_name: str = "pipeline",
               metadata: dict | None = None) -> int:
        """Write a Perfetto-loadable trace file; returns event count.
        `metadata` (see trace_metadata) makes the artifact
        self-describing for `aiko tune`."""
        document = chrome_trace_document(
            self.chrome_events(process_name=process_name),
            metadata=metadata)
        with open(path, "w") as handle:
            json.dump(document, handle)
        return len(document["traceEvents"])


def chrome_trace_document(events: list,
                          metadata: dict | None = None) -> dict:
    """Chrome-trace JSON document.  The optional `metadata` dict rides
    the spec's top-level "metadata" key under an "aiko" namespace --
    Perfetto/chrome://tracing ignore it, `aiko tune` requires it: a
    trace artifact that embeds its own pipeline definition + parameter
    fingerprint + bench config block is replayable with no side-channel
    files."""
    document = {"traceEvents": list(events), "displayTimeUnit": "ms"}
    if metadata is not None:
        document["metadata"] = {"aiko": metadata}
    return document


def definition_fingerprint(document: dict) -> str:
    """Stable content hash of a definition document (canonical JSON):
    the parameter fingerprint a trace is stamped with, so tune can
    tell whether a recommendation was computed against the SAME
    definition+parameters it is about to be applied to."""
    canonical = json.dumps(document, sort_keys=True, default=str)
    return "sha256:" + hashlib.sha256(
        canonical.encode("utf-8")).hexdigest()


def trace_metadata(definition_document: dict | None = None,
                   config: dict | None = None,
                   config_name: str | None = None,
                   metrics: dict | None = None,
                   clock_epoch: bool = False) -> dict:
    """Assemble the self-describing metadata block one trace artifact
    carries: the pipeline definition it was recorded under (with its
    fingerprint), the bench config block that produced it, and a
    metrics-registry snapshot taken at export.

    `clock_epoch=True` additionally stamps this process's
    clock_epoch_unix_us (what the fleet merger aligns timestamps
    with).  LIVE exporters (PipelineTelemetry / GatewayTelemetry) pass
    it; synthesized fixtures must not -- the stamp is wall-clock
    dependent and would break their byte-deterministic regeneration."""
    metadata: dict = {"schema": TRACE_METADATA_SCHEMA}
    if clock_epoch:
        metadata["clock_epoch_unix_us"] = round(
            clock_epoch_unix_us(), 3)
    if definition_document is not None:
        metadata["definition"] = definition_document
        metadata["fingerprint"] = definition_fingerprint(
            definition_document)
    if config is not None:
        metadata["config"] = config
    if config_name is not None:
        metadata["config_name"] = config_name
    if metrics is not None:
        metadata["metrics"] = metrics
    return metadata


def trace_metadata_of(document: dict) -> dict | None:
    """The aiko metadata block of a loaded trace document, or None for
    pre-metadata traces (any Chrome-trace JSON from another tool)."""
    if not isinstance(document, dict):
        return None
    metadata = document.get("metadata")
    if not isinstance(metadata, dict):
        return None
    aiko = metadata.get("aiko")
    return aiko if isinstance(aiko, dict) else None
