# PipelineTelemetry: the pipeline engine's single observability seam.
#
# One object per Pipeline owning a MetricsRegistry + frame Tracer, with
# every hot-path hook written so the DISABLED state (pipeline parameter
# `telemetry: false` -- the latency operating point) costs one attribute
# check and writes ZERO per-frame keys.  Enabled, the hooks keep the
# legacy `frame.metrics["time_*"]` keys byte-compatible (PE_Metrics and
# the bench latency math read them) while also feeding histograms,
# counters, and trace spans.
#
# Export: a periodic timer publishes the merged snapshot (pipeline
# registry + the process-global registry that the transfer plane and
# MQTT client write into) on `{topic_path}/metrics` -- matched by the
# Recorder's `{namespace}/+/+/+/metrics` subscription -- and mirrors a
# compact summary into the pipeline's EC share for dashboards.
#
# Span names/categories and the time_queue_* vs time_* key split
# follow THE taxonomy documented once in observe/trace.py.

from __future__ import annotations

import time

from ..utils import get_logger, truthy
from .metrics import MetricsRegistry, get_registry
from .trace import Tracer, now_us, to_us, trace_metadata

__all__ = ["PipelineTelemetry"]

_LOGGER = get_logger("telemetry")

DEFAULT_METRICS_INTERVAL = 10.0
# group-occupancy ladder: frames per coalesced call, not seconds
OCCUPANCY_BOUNDS = (1, 2, 4, 8, 16, 32, 64, 128)


class PipelineTelemetry:
    def __init__(self, pipeline):
        parameters = pipeline.definition.parameters or {}
        self.enabled = truthy(parameters.get("telemetry", True))
        self.pipeline = pipeline
        self.registry = MetricsRegistry()
        try:
            ring_size = int(parameters.get("trace_ring", 256))
        except (TypeError, ValueError):
            ring_size = 256
        self.tracer = Tracer(ring_size=ring_size)
        try:
            self._interval = float(parameters.get(
                "metrics_interval", DEFAULT_METRICS_INTERVAL) or 0.0)
        except (TypeError, ValueError):
            self._interval = DEFAULT_METRICS_INTERVAL
        self._timer = None
        # hot-path instrument handles resolved ONCE: per-frame hooks do
        # an attribute read + int add / bisect, never a name lookup
        # (the load heartbeat below is timer-driven, so `telemetry:
        # false` still means ZERO per-frame writes)
        registry = self.registry
        self._frames_total = registry.counter("pipeline.frames_total")
        self._frames_dropped = registry.counter(
            "pipeline.frames_dropped")
        self._frames_errored = registry.counter(
            "pipeline.frames_errored")
        self._fused_groups = registry.counter("pipeline.fused_groups")
        self._chained_groups = registry.counter(
            "pipeline.chained_groups")
        self._element_hists: dict = {}
        self._queue_hists: dict = {}
        if self._interval > 0:
            # with telemetry off only the cheap load heartbeat runs:
            # serving gateways age a replica's EC share (`stale_after`)
            # and would otherwise permanently distrust a healthy but
            # idle telemetry-disabled replica
            self._timer = (self._publish_snapshot if self.enabled
                           else self._publish_load)
            pipeline.process.event.add_timer_handler(
                self._timer, self._interval)

    # -- construction-time validation --------------------------------------

    def record_lint(self, report) -> None:
        """Static-analysis findings from construction-time validation:
        `lint.findings` plus a per-rule-code breakdown, so fleets can
        see definitions admitted WITH warnings (error findings never
        get here -- they fail construction).  Recorded even with
        telemetry disabled: this is a once-per-construction write, not
        a per-frame one, and a disabled-telemetry fleet still wants to
        know its definitions carry findings."""
        findings = getattr(report, "findings", None) or []
        self.registry.counter("lint.findings").inc(len(findings))
        for code, count in report.by_code().items():
            self.registry.counter(f"lint.findings.{code}").inc(count)

    # -- frame lifecycle ---------------------------------------------------

    def frame_begin(self, stream, frame, context: dict | None = None
                    ) -> None:
        if not self.enabled:
            return
        frame.trace = self.tracer.begin(stream.stream_id, frame.frame_id)
        if context is not None:
            # cross-process continuation: the gateway (or another
            # upstream hop) minted this trace -- keep its id, parent
            # our frame span under its span id
            frame.trace.adopt(context)

    def frame_end(self, stream, frame, dropped: bool = False,
                  error: bool = False) -> None:
        if not self.enabled:
            return
        self._frames_total.inc()
        if error:
            self._frames_errored.inc()
        elif dropped:
            self._frames_dropped.inc()
        trace = frame.trace
        if trace is not None:
            self.tracer.finish(
                trace, status=("error" if error
                               else "dropped" if dropped else "ok"))
            frame.trace = None

    # -- element execution -------------------------------------------------

    def record_element(self, frame, node: str, start_s: float,
                       elapsed_s: float, path: str = "inline",
                       group: int | None = None) -> None:
        """One element call finished: the legacy time_{node} key, the
        per-node latency histogram, and a trace span tagged with the
        dispatch path (inline / fused / chained / async / remote)."""
        if not self.enabled:
            return
        metrics = frame.metrics
        key = "time_" + node
        metrics[key] = metrics.get(key, 0.0) + elapsed_s
        histogram = self._element_hists.get(node)
        if histogram is None:
            histogram = self._element_hists[node] = (
                self.registry.histogram("element_s:" + node))
        histogram.record(elapsed_s)
        trace = frame.trace
        if trace is not None:
            args = {"path": path}
            if group is not None:
                args["group"] = group
            trace.events.append(
                ("X", node, "element", to_us(start_s), elapsed_s * 1e6,
                 args))

    def record_pipeline_pass(self, frame, start_s: float) -> None:
        if not self.enabled:
            return
        frame.metrics["time_pipeline"] = (
            frame.metrics.get("time_pipeline", 0.0)
            + time.perf_counter() - start_s)

    # -- parks, queues, resumes --------------------------------------------

    def mark_park(self, frame, node: str, kind: str) -> None:
        """A branch left the event loop (micro-batch park, async worker,
        remote hop).  Micro parks also open the queue-wait interval."""
        if not self.enabled:
            return
        trace = frame.trace
        if trace is None:
            return
        trace.instant(f"park:{node}", "park", {"kind": kind})
        if kind == "micro":
            trace.mark(node)

    def record_queue_wait(self, frame, node: str) -> None:
        """Close the park's queue-wait interval at flush time: the span
        between parking and the coalesced dispatch is scheduler-induced
        latency, reported apart from device/element time."""
        if not self.enabled:
            return
        trace = frame.trace
        if trace is None:
            return
        start = trace.take_mark(node)
        if start is None:
            return
        wait_s = (now_us() - start) / 1e6
        key = "time_queue_" + node
        frame.metrics[key] = frame.metrics.get(key, 0.0) + wait_s
        histogram = self._queue_hists.get(node)
        if histogram is None:
            histogram = self._queue_hists[node] = (
                self.registry.histogram("queue_s:" + node))
        histogram.record(wait_s)
        trace.events.append(
            ("X", f"queue:{node}", "queue", start, wait_s * 1e6, None))

    def mark_resume(self, frame, node: str,
                    elapsed_s: float | None = None,
                    path: str = "async") -> None:
        """A parked branch resumed (async reply or remote response);
        `elapsed_s` is the off-loop work time the reply reported and
        `path` attributes the span (async worker vs remote hop)."""
        if not self.enabled:
            return
        if elapsed_s is not None:
            key = "time_" + node
            frame.metrics[key] = frame.metrics.get(key, 0.0) + elapsed_s
            histogram = self._element_hists.get(node)
            if histogram is None:
                histogram = self._element_hists[node] = (
                    self.registry.histogram("element_s:" + node))
            histogram.record(elapsed_s)
        trace = frame.trace
        if trace is not None:
            if elapsed_s is not None:
                trace.events.append(
                    ("X", node, "element", now_us() - elapsed_s * 1e6,
                     elapsed_s * 1e6, {"path": path}))
            trace.instant(f"resume:{node}", "park", None)

    def record_engine_frame(self, frame, node: str, stats_rows) -> None:
        """A continuous-batching engine (LMGenerate `continuous: true`)
        finished every row of a frame: per-slot spans (queue_wait /
        prefill / decode_steps) reconstructed from the engine's
        completion stats onto the frame trace, so Perfetto shows where
        each request's lifetime went even though the engine ran it
        interleaved with other frames' slots."""
        if not self.enabled:
            return
        # queue-wait vs compute split, SAME keys as the micro-batch
        # paths: time_queue_{node} is scheduler/slot-induced wait (the
        # frame completes when its slowest row does, so the frame's
        # wait is the MAX row wait), and the response-side time_{node}
        # (mark_resume) carries compute excluding that wait -- tune's
        # attribution reads these keys identically on the fused,
        # chained, and engine-managed paths
        queue_wait_s = max(
            (float(stats.get("queue_wait_s", 0.0))
             for stats in stats_rows), default=0.0)
        key = "time_queue_" + node
        frame.metrics[key] = frame.metrics.get(key, 0.0) + queue_wait_s
        histogram = self._queue_hists.get(node)
        if histogram is None:
            histogram = self._queue_hists[node] = (
                self.registry.histogram("queue_s:" + node))
        histogram.record(queue_wait_s)
        trace = frame.trace
        if trace is None:
            return
        end = now_us()
        for row, stats in enumerate(stats_rows):
            total = float(stats.get("total_s", 0.0)) * 1e6
            queue = float(stats.get("queue_wait_s", 0.0)) * 1e6
            prefill = float(stats.get("prefill_s", 0.0)) * 1e6
            start = end - total
            suffix = f"[{row}]" if len(stats_rows) > 1 else ""
            trace.events.append(
                ("X", f"queue:{node}{suffix}", "queue", start, queue,
                 None))
            # prefix-cache evidence rides the prefill span: the loader
            # turns prefix_blocks into per-element hit evidence so the
            # tune model can tell a CACHE-BOUND prefill floor (most of
            # the prompt skipped) from a compute-bound one
            prefill_args = None
            if stats.get("prefix_blocks") is not None:
                prefill_args = {
                    "prefix_blocks": stats.get("prefix_blocks")}
            trace.events.append(
                ("X", f"prefill:{node}{suffix}", "engine", start + queue,
                 prefill, prefill_args))
            trace.events.append(
                ("X", f"decode_steps:{node}{suffix}", "engine",
                 start + queue + prefill,
                 max(total - queue - prefill, 0.0),
                 {"decode_steps": stats.get("decode_steps"),
                  "preemptions": stats.get("preemptions"),
                  "tokens": stats.get("tokens")}))

    def record_adopt(self, stream, frame_id, node: str,
                     elapsed_s: float,
                     parent: dict | None = None) -> None:
        """A disaggregated decode element adopted a frame's migrated
        KV blocks (fetch + pool scatter): its own span category so
        `aiko tune` classifies migration-bound elements distinctly
        from queue-bound ones.  `parent` is the prefill hop's trace
        context (it rode the handoff descriptor), recorded as the
        span's cross-process parent link."""
        if not self.enabled:
            return
        self.registry.histogram("adopt_s:" + node).record(elapsed_s)
        frame = (stream.frames.get(frame_id)
                 if stream is not None else None)
        trace = frame.trace if frame is not None else None
        if trace is not None:
            args = None
            if parent and parent.get("span_id"):
                args = {"parent": str(parent["span_id"])}
            trace.events.append(
                ("X", f"adopt:{node}", "engine",
                 now_us() - elapsed_s * 1e6, elapsed_s * 1e6, args))

    def record_checkpoint(self, node: str, elapsed_s: float,
                          checkpoint_bytes: int) -> None:
        """One decode-state snapshot shipped (decode/checkpoint.py):
        per-node latency histogram plus a GLOBAL engine span -- the
        snapshot covers every due slot, so it belongs to no single
        frame -- which the tune loader joins as `checkpoint:{node}`
        and the classifier labels checkpoint-bound when it dominates
        compute/queue/adopt."""
        if not self.enabled:
            return
        self.registry.histogram("checkpoint_s:" + node).record(
            elapsed_s)
        self.tracer.span_global(
            f"checkpoint:{node}", "engine", elapsed_s,
            {"bytes": int(checkpoint_bytes)})

    # -- fault tolerance ---------------------------------------------------

    def record_retry(self, frame, node: str, attempt: int,
                     delay_s: float) -> None:
        """One element call failed and was scheduled for retry under the
        `on_error: retry` policy."""
        if not self.enabled:
            return
        self.registry.counter("pipeline.retries").inc()
        self.registry.counter(f"retries:{node}").inc()
        trace = frame.trace
        if trace is not None:
            trace.instant(f"retry:{node}", "fault",
                          {"attempt": attempt,
                           "delay_ms": round(delay_s * 1000, 3)})

    def record_dead_letter(self, node: str | None, reason: str) -> None:
        if not self.enabled:
            return
        self.registry.counter("pipeline.dead_letters").inc()
        self.registry.counter(f"dead_letters:{reason}").inc()

    def record_park_expired(self, frame, nodes) -> None:
        """The doubtful-park watchdog released a frame: kills must show
        up in telemetry, not only as a log line."""
        if not self.enabled:
            return
        self.registry.counter("pipeline.park_expired").inc()
        trace = frame.trace
        if trace is not None:
            trace.instant("park_expired", "fault",
                          {"nodes": sorted(str(n) for n in nodes)})

    def record_deadline_expired(self, frame) -> None:
        if not self.enabled:
            return
        self.registry.counter("pipeline.deadline_expired").inc()
        trace = frame.trace
        if trace is not None:
            trace.instant("frame_deadline", "fault",
                          {"pending": sorted(str(n) for n
                                             in frame.pending_nodes)})

    def record_stream_collision(self, stream_id: str) -> None:
        """create_stream hit an already-registered stream_id with
        DIFFERENT parameters: the caller got the existing stream, not
        one configured as requested -- counted so id-allocation bugs
        upstream (two clients minting the same id) surface in metrics,
        not only in one warning line."""
        if not self.enabled:
            return
        self.registry.counter("pipeline.stream_id_collision").inc()

    def record_breaker_trip(self, stream_id: str) -> None:
        """A stream blew its error budget and was quarantined."""
        if not self.enabled:
            return
        self.registry.counter("pipeline.breaker_trips").inc()
        self.tracer.instant_global(f"breaker:{stream_id}", "fault", None)

    def record_fused_failure(self, node: str, disabled: bool) -> None:
        """A fused group program failed at run time (the group retried
        on the chained path); `disabled` marks the flap limit tripping,
        after which the element runs chained permanently."""
        if not self.enabled:
            return
        self.registry.counter("pipeline.fused_failures").inc()
        if disabled:
            self.registry.counter("pipeline.fused_disabled").inc()
            self.tracer.instant_global(f"fused_disabled:{node}", "fault",
                                       None)

    # -- micro-batch scheduler ---------------------------------------------

    def record_group(self, node: str, size: int, rows: int,
                     fused: bool) -> None:
        if not self.enabled:
            return
        (self._fused_groups if fused else self._chained_groups).inc()
        self.registry.histogram(
            f"group_frames:{node}", OCCUPANCY_BOUNDS).record(size)
        self.registry.histogram(
            f"group_rows:{node}", OCCUPANCY_BOUNDS).record(rows)

    def record_compile(self, node: str, what: str) -> None:
        if not self.enabled:
            return
        self.registry.counter(f"pipeline.compiles_{what}").inc()
        self.tracer.instant_global(f"compile:{node}", "compile",
                                   {"what": what})

    def record_cohort_split(self, node: str, cohorts: int) -> None:
        if not self.enabled:
            return
        self.registry.counter("pipeline.cohort_splits").inc()
        self.registry.gauge(f"cohorts:{node}").set(cohorts)

    # -- element-side device instruments -----------------------------------

    def record_device(self, node: str, compute_s: float,
                      block_ready_s: float | None = None) -> None:
        """ComputeElement device work: host-observed dispatch+compute
        time, plus the explicit block_until_ready wait when the element
        runs with blocking_metrics."""
        if not self.enabled:
            return
        self.registry.histogram(f"compute_s:{node}").record(compute_s)
        if block_ready_s is not None:
            self.registry.histogram(
                f"block_ready_s:{node}").record(block_ready_s)

    # -- export ------------------------------------------------------------

    def snapshot(self) -> dict:
        """THIS pipeline's registry only (see process_snapshot)."""
        return self.registry.snapshot()

    @staticmethod
    def process_snapshot() -> dict:
        """The process-global registry (transfer plane, MQTT client).
        Published under a PROCESS-scoped source name, never merged into
        a pipeline's snapshot: N pipelines in one process would
        otherwise each republish the same global counters and the
        Recorder's fleet merge would count them N times."""
        return get_registry().snapshot()

    def summary(self) -> dict:
        """Compact scalars for the EC share / dashboard plugin.  The
        `load` sub-dict is the serving gateway's periodic load gauge: a
        remote gateway admits/routes against these numbers (refreshed
        every metrics_interval) between the create/destroy-time share
        updates."""
        summary = {
            "load": self.pipeline.load(),
            "frames": self._frames_total.value,
            "dropped": self._frames_dropped.value,
            "errors": self._frames_errored.value,
            "fused_groups": self._fused_groups.value,
            "chained_groups": self._chained_groups.value,
            "compiles_fused": self.registry.counter(
                "pipeline.compiles_fused").value,
            "cohort_splits": self.registry.counter(
                "pipeline.cohort_splits").value,
            "retries": self.registry.counter("pipeline.retries").value,
            "dead_letters": self.registry.counter(
                "pipeline.dead_letters").value,
        }
        decode = self.decode_summary()
        if decode is not None:
            summary["decode"] = decode
        exports = self.registry.counter("prefill.exports").value
        if exports:
            # a prefill-pool replica (LMGenerate role=prefill): its
            # export/queue numbers are what the disagg autoscaler's
            # queue-pressure signal and the dashboard read
            summary["prefill"] = {
                "exports": exports,
                "exported_bytes": self.registry.counter(
                    "prefill.exported_bytes").value,
                "chunks": self.registry.counter(
                    "prefill.chunks").value,
            }
        return summary

    def decode_summary(self) -> dict | None:
        """Continuous-batching engine scalars (decode/ gauges +
        counters) for the EC share, so slot occupancy is visible PER
        REPLICA on the dashboard services page and to the gateway's
        ECConsumer mirrors -- not only on the live-metrics page.  None
        when no engine has registered (the common non-LLM pipeline)."""
        if not self.registry.has_gauge("decode.active_slots"):
            return None
        summary = {
            "active_slots": self.registry.gauge(
                "decode.active_slots").value,
            "free_blocks": self.registry.gauge(
                "decode.free_blocks").value,
            "waiting": self.registry.gauge("decode.waiting").value,
            "admitted": self.registry.counter("decode.admitted").value,
            "completed": self.registry.counter("decode.completed").value,
            "preempted": self.registry.counter("decode.preempted").value,
            "deferred": self.registry.counter(
                "decode.deferred_admissions").value,
        }
        # kernel-floor features surface only when in use, so the
        # summary shape of a plain engine stays unchanged
        chunks = self.registry.counter("decode.prefill_chunks").value
        if chunks:
            summary["prefill_chunks"] = chunks
            summary["chunk_interleaves"] = self.registry.counter(
                "decode.chunk_interleaves").value
        drafted = self.registry.counter("decode.spec_drafted").value
        if drafted:
            accepted = self.registry.counter(
                "decode.spec_accepted").value
            windows = max(
                self.registry.histogram("decode.accepted_len").count, 1)
            summary["accepted_len_mean"] = round(accepted / windows, 3)
        adopted = self.registry.counter("decode.adopted").value
        fallbacks = self.registry.counter(
            "decode.adopt_fallbacks").value
        if adopted or fallbacks:
            # disaggregated decode pool: KV migrations in, and the
            # degradations the prefill-pool autoscaler watches
            summary["adopted"] = adopted
            summary["adopt_fallbacks"] = fallbacks
            summary["kv_migrated_bytes"] = self.registry.counter(
                "decode.kv_migrated_bytes").value
        checkpoints = self.registry.counter("decode.checkpoints").value
        if checkpoints:
            # warm KV failover: snapshot cadence + the restore ledger
            # (restores = re-prefills avoided; fallbacks = degraded)
            summary["checkpoints"] = checkpoints
            summary["checkpoint_bytes"] = self.registry.counter(
                "decode.checkpoint_bytes").value
        restores = self.registry.counter("decode.restores").value
        restore_fallbacks = self.registry.counter(
            "decode.restore_fallbacks").value
        if restores or restore_fallbacks:
            summary["restores"] = restores
            summary["restore_fallbacks"] = restore_fallbacks
            summary["restore_replayed_tokens"] = self.registry.counter(
                "decode.restore_replayed_tokens").value
        return summary

    def _publish_snapshot(self) -> None:
        pipeline = self.pipeline
        try:
            from ..utils import generate
            topic = f"{pipeline.topic_path}/metrics"
            pipeline.process.publish(
                topic, generate("metrics",
                                [pipeline.topic_path, self.snapshot()]))
            # the process-global registry rides the same topic under an
            # OS-process-scoped source: every pipeline (and every
            # framework Process object sharing this interpreter)
            # republishes it, but consumers key by SOURCE, so it merges
            # exactly once.  os.getpid(), NOT process.process_id: a
            # second Process object in one interpreter gets a "-1"
            # suffixed id while sharing the SAME global registry
            import os
            pipeline.process.publish(
                topic, generate("metrics", [
                    f"{pipeline.process.namespace}/"
                    f"{pipeline.process.hostname}/{os.getpid()}/process",
                    self.process_snapshot()]))
            if pipeline.ec_producer is not None:
                summary = self.summary()
                # COALESCED: the summary + load scalars fold into ONE
                # delta payload per lease per tick (stage/flush), with
                # unchanged scalars dropped from the payload -- the
                # telemetry tick costs one control-plane publish per
                # consumer, not three.  The serving gateway's
                # ECConsumer mirror reads plain `inflight` /
                # `queue_depth` keys (nested dicts are awkward over the
                # EC wire), refreshed here between stream-churn updates
                load = summary.get("load") or {}
                pipeline.ec_producer.stage("metrics", summary)
                pipeline.ec_producer.stage(
                    "inflight", load.get("inflight", 0))
                pipeline.ec_producer.stage(
                    "queue_depth", load.get("queue_depth", 0))
        except Exception as error:  # export must never kill the engine
            _LOGGER.warning("metrics publish failed: %s", error)

    def _publish_load(self) -> None:
        """The telemetry-disabled heartbeat: refresh ONLY the EC share
        load scalars (no registry snapshot, no tracing, nothing
        per-frame touched)."""
        pipeline = self.pipeline
        try:
            if pipeline.ec_producer is not None:
                load = pipeline.load()
                # staged, with `inflight` forced: one delta payload per
                # heartbeat (the forced key keeps the gateway's
                # staleness clock -- ECConsumer.last_update -- ticking
                # for an idle replica whose load never changes)
                pipeline.ec_producer.stage(
                    "inflight", load.get("inflight", 0), force=True)
                pipeline.ec_producer.stage(
                    "queue_depth", load.get("queue_depth", 0))
        except Exception as error:
            _LOGGER.warning("load heartbeat failed: %s", error)

    def stop(self) -> None:
        if self._timer is not None:
            self.pipeline.process.event.remove_timer_handler(self._timer)
            self._timer = None
            if self.enabled:
                self._publish_snapshot()  # final flush: no stale window

    # -- trace export ------------------------------------------------------

    def chrome_events(self) -> list:
        return self.tracer.chrome_events(
            process_name=f"pipeline:{self.pipeline.name}")

    def trace_metadata(self, config: dict | None = None,
                       config_name: str | None = None) -> dict:
        """Self-describing metadata for this pipeline's trace export:
        the definition document (reconstructed from the live
        definition, so applied parameter updates are captured), its
        fingerprint, and a metrics snapshot -- everything `aiko tune`
        needs to replay the trace with no side-channel files."""
        from ..pipeline.definition import definition_to_document
        metadata = trace_metadata(
            definition_document=definition_to_document(
                self.pipeline.definition),
            config=config, config_name=config_name,
            metrics=self.snapshot(), clock_epoch=True)
        # this tracer's synthetic pid: when several pipelines' events
        # share one artifact (bench combined file, router replicas),
        # the tune loader filters spans to the selected run's pids
        metadata["pids"] = [self.tracer._pid]
        return metadata

    def export_trace(self, path: str, config: dict | None = None,
                     config_name: str | None = None) -> int:
        return self.tracer.export(
            path, process_name=f"pipeline:{self.pipeline.name}",
            metadata=self.trace_metadata(config=config,
                                         config_name=config_name))
