# Pipeline definitions: the JSON document describing a pipeline graph.
#
# Capability parity with the reference definition layer (reference:
# src/aiko_services/main/pipeline.py:142-178 dataclasses and the embedded
# Avro schema :1323-1440): a pipeline has a name, a graph (S-expression path
# list), pipeline-level parameters, and element definitions with typed
# input/output ports and a deploy block that is either local
# {module, class_name} or remote {service_filter}.  Validation is hand-rolled
# schema checking (explicit error messages instead of Avro), plus the graph /
# port cross-checks the reference does in PipelineGraph.validate
# (reference pipeline.py:254-286) including map_in/map_out renames.
#
# TPU-first addition: element definitions may carry a "sharding" block
# naming mesh axes for the element's compute (data/model/sequence), consumed
# by parallel/mesh.py -- the reference has no counterpart (SURVEY.md 2.4).

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path

from ..utils import Graph

__all__ = [
    "PipelineDefinition", "ElementDefinition", "DefinitionError",
    "parse_pipeline_definition", "validate_pipeline_definition",
]


class DefinitionError(ValueError):
    pass


@dataclass
class ElementDefinition:
    name: str
    input: list = field(default_factory=list)    # [{"name":..,"type":..}]
    output: list = field(default_factory=list)
    parameters: dict = field(default_factory=dict)
    deploy_local: dict | None = None     # {"module":.., "class_name":..}
    deploy_remote: dict | None = None    # {"service_filter": {...}}
    map_in: dict = field(default_factory=dict)   # input_name -> swag_key
    map_out: dict = field(default_factory=dict)  # output_name -> swag_key
    sharding: dict = field(default_factory=dict)  # TPU mesh axes block

    @property
    def is_local(self) -> bool:
        return self.deploy_local is not None

    def input_names(self) -> list[str]:
        return [port["name"] for port in self.input]

    def output_names(self) -> list[str]:
        return [port["name"] for port in self.output]


@dataclass
class PipelineDefinition:
    name: str
    version: int = 0
    runtime: str = "jax"
    graph: list = field(default_factory=list)
    parameters: dict = field(default_factory=dict)
    elements: list = field(default_factory=list)

    def element(self, name: str) -> ElementDefinition | None:
        for definition in self.elements:
            if definition.name == name:
                return definition
        return None


def _require(condition, message):
    if not condition:
        raise DefinitionError(message)


def _parse_ports(ports, element_name, direction) -> list:
    _require(isinstance(ports, list),
             f"{element_name}: '{direction}' must be a list")
    parsed = []
    for port in ports:
        _require(isinstance(port, dict) and "name" in port,
                 f"{element_name}: each {direction} port needs a 'name'")
        parsed.append({"name": port["name"],
                       "type": port.get("type", "any"),
                       # micro-batch contract: batched outputs are split
                       # per frame by leading-row range; "batched": false
                       # marks an output as shared by every coalesced
                       # frame even when its leading dim happens to match
                       # the batch size (e.g. an NxN affinity matrix)
                       "batched": bool(port.get("batched", True))})
    return parsed


def parse_pipeline_definition(source) -> PipelineDefinition:
    """source: dict, JSON text, or a path to a JSON file."""
    if isinstance(source, (str, Path)) and str(source).endswith(".json"):
        with open(source) as handle:
            document = json.load(handle)
    elif isinstance(source, str):
        document = json.loads(source)
    else:
        document = source
    _require(isinstance(document, dict), "Definition must be a JSON object")
    _require("name" in document, "Definition needs a 'name'")
    _require("graph" in document and isinstance(document["graph"], list)
             and document["graph"],
             "Definition needs a non-empty 'graph' list")
    _require("elements" in document and isinstance(document["elements"], list),
             "Definition needs an 'elements' list")

    elements = []
    for record in document["elements"]:
        _require(isinstance(record, dict) and "name" in record,
                 "Each element needs a 'name'")
        name = record["name"]
        deploy = record.get("deploy", {})
        local = deploy.get("local")
        remote = deploy.get("remote")
        _require((local is None) != (remote is None),
                 f"{name}: deploy must be exactly one of local|remote")
        if local is not None:
            _require("module" in local and "class_name" in local,
                     f"{name}: deploy.local needs module and class_name")
        else:
            _require("service_filter" in remote,
                     f"{name}: deploy.remote needs service_filter")
        elements.append(ElementDefinition(
            name=name,
            input=_parse_ports(record.get("input", []), name, "input"),
            output=_parse_ports(record.get("output", []), name, "output"),
            parameters=record.get("parameters", {}),
            deploy_local=local,
            deploy_remote=remote,
            map_in=record.get("map_in", {}),
            map_out=record.get("map_out", {}),
            sharding=record.get("sharding", {}),
        ))

    definition = PipelineDefinition(
        name=document["name"],
        version=int(document.get("version", 0)),
        runtime=document.get("runtime", "jax"),
        graph=list(document["graph"]),
        parameters=document.get("parameters", {}),
        elements=elements,
    )
    validate_pipeline_definition(definition)
    return definition


def validate_pipeline_definition(definition: PipelineDefinition) -> Graph:
    """Cross-check the graph against element definitions and port linking.

    Mirrors the reference PipelineGraph.validate (pipeline.py:254-286):
    every input of a non-head element must be produced by some predecessor's
    output (after map_in/map_out renames) or supplied as initial frame data
    for head elements.
    """
    names = [element.name for element in definition.elements]
    _require(len(names) == len(set(names)),
             f"Duplicate element names in {definition.name}")
    # fault-tolerance grammar: a mistyped on_error would silently fall
    # back to stop_stream at runtime -- reject it at definition time,
    # wherever it is declared (pipeline-wide or per element)
    from .element import ERROR_POLICIES
    for scope_name, parameters in (
            [(definition.name, definition.parameters)]
            + [(element.name, element.parameters)
               for element in definition.elements]):
        on_error = (parameters or {}).get("on_error")
        _require(
            on_error is None or str(on_error).lower() in ERROR_POLICIES,
            f"{scope_name}: on_error must be one of {ERROR_POLICIES}, "
            f"got {on_error!r}")
    graph = Graph.traverse(definition.graph)
    for node_name in graph.node_names():
        _require(definition.element(node_name) is not None,
                 f"Graph node '{node_name}' has no element definition")

    heads = set(graph.head_nodes())
    for node_name in graph.get_path():
        element = definition.element(node_name)
        if node_name in heads:
            continue  # head inputs come from create_frame data
        available = set()
        for predecessor in _ancestors(graph, node_name):
            predecessor_def = definition.element(predecessor)
            for output_name in predecessor_def.output_names():
                available.add(
                    predecessor_def.map_out.get(output_name, output_name))
        for input_name in element.input_names():
            swag_key = element.map_in.get(input_name, input_name)
            _require(
                swag_key in available,
                f"{definition.name}: element '{node_name}' input "
                f"'{input_name}' (swag key '{swag_key}') is not produced by "
                f"any ancestor; available: {sorted(available)}")
    return graph


def _ancestors(graph: Graph, name: str) -> set:
    result = set()
    frontier = list(graph.predecessors(name))
    while frontier:
        node = frontier.pop()
        if node in result:
            continue
        result.add(node)
        frontier.extend(graph.predecessors(node))
    return result
