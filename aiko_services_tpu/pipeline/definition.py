# Pipeline definitions: the JSON document describing a pipeline graph.
#
# Capability parity with the reference definition layer (reference:
# src/aiko_services/main/pipeline.py:142-178 dataclasses and the embedded
# Avro schema :1323-1440): a pipeline has a name, a graph (S-expression path
# list), pipeline-level parameters, and element definitions with typed
# input/output ports and a deploy block that is either local
# {module, class_name} or remote {service_filter}.  Validation is hand-rolled
# schema checking (explicit error messages instead of Avro), plus the graph /
# port cross-checks the reference does in PipelineGraph.validate
# (reference pipeline.py:254-286) including map_in/map_out renames.
#
# TPU-first addition: element definitions may carry a "sharding" block
# naming mesh axes for the element's compute (data/model/sequence), consumed
# by parallel/mesh.py -- the reference has no counterpart (SURVEY.md 2.4).

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path

from ..utils import Graph

__all__ = [
    "PipelineDefinition", "ElementDefinition", "DefinitionError",
    "definition_to_document", "parse_pipeline_definition",
    "validate_pipeline_definition",
]


class DefinitionError(ValueError):
    pass


@dataclass
class ElementDefinition:
    name: str
    input: list = field(default_factory=list)    # [{"name":..,"type":..}]
    output: list = field(default_factory=list)
    parameters: dict = field(default_factory=dict)
    deploy_local: dict | None = None     # {"module":.., "class_name":..}
    deploy_remote: dict | None = None    # {"service_filter": {...}}
    map_in: dict = field(default_factory=dict)   # input_name -> swag_key
    map_out: dict = field(default_factory=dict)  # output_name -> swag_key
    sharding: dict = field(default_factory=dict)  # TPU mesh axes block

    @property
    def is_local(self) -> bool:
        return self.deploy_local is not None

    def input_names(self) -> list[str]:
        return [port["name"] for port in self.input]

    def output_names(self) -> list[str]:
        return [port["name"] for port in self.output]


@dataclass
class PipelineDefinition:
    name: str
    version: int = 0
    runtime: str = "jax"
    graph: list = field(default_factory=list)
    parameters: dict = field(default_factory=dict)
    elements: list = field(default_factory=list)

    def element(self, name: str) -> ElementDefinition | None:
        for definition in self.elements:
            if definition.name == name:
                return definition
        return None


def definition_to_document(definition: PipelineDefinition) -> dict:
    """The inverse of parse_pipeline_definition: a JSON-able document
    that re-parses to an equivalent definition.  Used by the trace
    exporter (a Perfetto artifact embeds the definition it was recorded
    under, so `aiko tune` can replay it without side-channel files) and
    by `aiko tune --apply` (recommendations are written back as a
    definition document and re-linted)."""
    elements = []
    for element in definition.elements:
        record: dict = {"name": element.name}
        if element.input:
            record["input"] = [dict(port) for port in element.input]
        if element.output:
            record["output"] = [dict(port) for port in element.output]
        if element.parameters:
            record["parameters"] = dict(element.parameters)
        if element.map_in:
            record["map_in"] = dict(element.map_in)
        if element.map_out:
            record["map_out"] = dict(element.map_out)
        if element.sharding:
            record["sharding"] = dict(element.sharding)
        record["deploy"] = (
            {"local": dict(element.deploy_local)}
            if element.deploy_local is not None
            else {"remote": dict(element.deploy_remote or {})})
        elements.append(record)
    document = {
        "name": definition.name,
        "graph": list(definition.graph),
        "elements": elements,
    }
    if definition.version:
        document["version"] = definition.version
    if definition.runtime != "jax":
        document["runtime"] = definition.runtime
    if definition.parameters:
        document["parameters"] = dict(definition.parameters)
    return document


def _require(condition, message):
    if not condition:
        raise DefinitionError(message)


def _parse_ports(ports, element_name, direction) -> list:
    _require(isinstance(ports, list),
             f"{element_name}: '{direction}' must be a list")
    parsed = []
    for port in ports:
        _require(isinstance(port, dict) and "name" in port,
                 f"{element_name}: each {direction} port needs a 'name'")
        record = {"name": port["name"],
                  "type": port.get("type", "any"),
                  # micro-batch contract: batched outputs are split
                  # per frame by leading-row range; "batched": false
                  # marks an output as shared by every coalesced
                  # frame even when its leading dim happens to match
                  # the batch size (e.g. an NxN affinity matrix)
                  "batched": bool(port.get("batched", True))}
        # "optional": true inputs bind to None when the frame carries
        # no such key instead of erroring the frame (the disagg decode
        # element's `handoff` port: present on migrated frames, absent
        # on direct ones).  Only recorded when set, so existing
        # definitions round-trip byte-identically
        if port.get("optional"):
            record["optional"] = True
        parsed.append(record)
    return parsed


def _looks_like_path(source) -> bool:
    """Filesystem-path sniffing: an existing file is ALWAYS a path
    (whatever its suffix -- definitions ship as .json, .pipeline, or
    extensionless), and a .json suffix is a path even when the file is
    missing, so the error names the file instead of a JSONDecodeError
    over the path string."""
    if isinstance(source, Path):
        return True
    text = str(source)
    if text.endswith(".json"):
        return True
    if "\n" in text or text.lstrip()[:1] in ("{", "["):
        return False  # JSON text, never a legal path probe
    try:
        return Path(text).exists()
    except OSError:
        return False  # e.g. a name longer than the filesystem allows


def parse_pipeline_definition(source,
                              validate: bool = True) -> PipelineDefinition:
    """source: dict, JSON text, or a path to a JSON file.

    `validate=False` parses the schema only (the static analyzer lints
    unvalidated definitions so EVERY problem is reported, not just the
    first)."""
    if isinstance(source, (str, Path)) and _looks_like_path(source):
        path = Path(str(source))
        try:
            with open(path) as handle:
                document = json.load(handle)
        except OSError as error:
            raise DefinitionError(
                f"cannot read definition file {path}: {error}") from None
        except json.JSONDecodeError as error:
            raise DefinitionError(
                f"definition file {path} is not valid JSON: "
                f"{error}") from None
    elif isinstance(source, str):
        try:
            document = json.loads(source)
        except json.JSONDecodeError as error:
            raise DefinitionError(
                f"definition is neither an existing file nor valid "
                f"JSON text: {error}") from None
    else:
        document = source
    _require(isinstance(document, dict), "Definition must be a JSON object")
    _require("name" in document, "Definition needs a 'name'")
    _require("graph" in document and isinstance(document["graph"], list)
             and document["graph"],
             "Definition needs a non-empty 'graph' list")
    _require("elements" in document and isinstance(document["elements"], list),
             "Definition needs an 'elements' list")

    elements = []
    for record in document["elements"]:
        _require(isinstance(record, dict) and "name" in record,
                 "Each element needs a 'name'")
        name = record["name"]
        deploy = record.get("deploy", {})
        local = deploy.get("local")
        remote = deploy.get("remote")
        _require((local is None) != (remote is None),
                 f"{name}: deploy must be exactly one of local|remote")
        if local is not None:
            _require("module" in local and "class_name" in local,
                     f"{name}: deploy.local needs module and class_name")
        else:
            _require("service_filter" in remote,
                     f"{name}: deploy.remote needs service_filter")
        elements.append(ElementDefinition(
            name=name,
            input=_parse_ports(record.get("input", []), name, "input"),
            output=_parse_ports(record.get("output", []), name, "output"),
            parameters=record.get("parameters", {}),
            deploy_local=local,
            deploy_remote=remote,
            map_in=record.get("map_in", {}),
            map_out=record.get("map_out", {}),
            sharding=record.get("sharding", {}),
        ))

    definition = PipelineDefinition(
        name=document["name"],
        version=int(document.get("version", 0)),
        runtime=document.get("runtime", "jax"),
        graph=list(document["graph"]),
        parameters=document.get("parameters", {}),
        elements=elements,
    )
    if validate:
        validate_pipeline_definition(definition)
    return definition


# the graph-pass rules that mirror the reference PipelineGraph.validate
# (pipeline.py:254-286): structural wiring errors every caller of
# parse(validate=True) has always been rejected on.  AIKO2xx spec-flow
# errors are deliberately NOT in this set -- typed-port checking is the
# construction-lint/`aiko lint` surface, and legacy callers parse
# untyped definitions.
_STRUCTURAL_CODES = frozenset(
    ["AIKO101", "AIKO102", "AIKO103", "AIKO105", "AIKO106", "AIKO107"])


def validate_pipeline_definition(definition: PipelineDefinition) -> Graph:
    """Cross-check the graph against element definitions and port
    linking: every input of a non-head element must be produced by some
    ancestor's output (after map_in/map_out renames) or supplied as
    initial frame data for head elements.

    The structural rules are the analyzer's graph pass
    (analyze/graph_flow.py AIKO1xx) filtered to _STRUCTURAL_CODES, so
    this error and `aiko lint` can never drift; the on_error grammar
    rides the shared directive-grammar core the same way (AIKO401)."""
    # fault-tolerance grammar: a mistyped on_error would silently fall
    # back to stop_stream at runtime -- reject it at definition time,
    # wherever it is declared (pipeline-wide or per element)
    from ..analyze.grammar import Field, GrammarError
    from .element import ERROR_POLICIES
    on_error_field = Field("str", choices=ERROR_POLICIES)
    for scope_name, parameters in (
            [(definition.name, definition.parameters)]
            + [(element.name, element.parameters)
               for element in definition.elements]):
        on_error = (parameters or {}).get("on_error")
        if on_error is not None:
            try:
                on_error_field.coerce(definition.name, "on_error",
                                      str(on_error).lower())
            except GrammarError as error:
                raise DefinitionError(f"{scope_name}: {error}") from None
    graph = Graph.traverse(definition.graph)
    from ..analyze.graph_flow import run_graph_pass
    report = run_graph_pass(definition, graph=graph)
    problems = [diagnostic for diagnostic in report.findings
                if diagnostic.code in _STRUCTURAL_CODES]
    if problems:
        raise DefinitionError(
            f"{definition.name}: "
            + "\n".join(diagnostic.render() for diagnostic in problems))
    return graph
