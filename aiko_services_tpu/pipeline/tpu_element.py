# ComputeElement: the TPU compute contract for pipeline elements.
#
# This layer has no reference counterpart -- the reference's elements call
# torch/CUDA libraries ad hoc inside process_frame (reference:
# src/aiko_services/examples/yolo/yolo.py:51-87,
# examples/speech/speech_elements.py:229-262).  Here element math is a PURE
# JAX function compiled once per shape bucket:
#
#   class MyElement(ComputeElement):
#       def setup(self) -> state:            # build params (pytree) once
#       def compute(self, state, **inputs):  # pure jax fn -> outputs dict
#       def dynamic_parameters(self, stream) -> dict   # optional: traced
#           # per-frame values (live-updatable without recompiling)
#
# The engine: places state on the element's mesh (definition "sharding"
# block) with NamedSharding; jits compute; pads variable axes to
# power-of-two buckets so jit's shape-keyed cache stays small and un-pads
# matching output axes afterwards; keeps outputs on device (jax.Array in
# the swag) so a downstream ComputeElement never touches the host.
#
# Parameter semantics: plain get_parameter() reads inside compute() are
# baked in at trace time (cheap, but live updates need a recompile); values
# returned from dynamic_parameters() are fed as traced 0-d arrays each
# frame, so dashboard/EC updates apply immediately at zero recompile cost.

from __future__ import annotations

import contextlib
import inspect
import time

import jax
import jax.numpy as jnp
import numpy as np

from ..parallel.mesh import get_mesh, named_sharding, shard_pytree
from ..utils import get_logger
from ..utils.padding import bucket_length, pad_axis_to  # noqa: F401
from .element import PipelineElement
from .stream import Stream, StreamEvent

__all__ = ["ComputeElement", "bucket_length", "pad_axis_to"]

_LOGGER = get_logger("tpu_element")


class ComputeElement(PipelineElement):
    """PipelineElement whose math is a pure, jit-compiled JAX function.

    Definition parameters understood by the engine:
      sharding:        {"axes": {"data": -1, ...},
                        "state": <spec or pytree of specs>,
                        "inputs": {input_name: spec}}
      bucket_axes:     {input_name: axis_index} -- pad that axis to a bucket
      bucket_min:      minimum bucket size (default 16)
      buckets:         explicit bucket ladder, e.g. [128, 512, 2048]
      unpad_outputs:   slice bucket padding off outputs whose bucketed axis
                       matches the padded input size (default True)
      blocking_metrics: bool -- block_until_ready inside the timing window

    If compute() declares a `lengths` keyword, the engine passes a dict
    {input_name: int32 scalar} of pre-padding lengths so kernels can mask
    padded positions.
    """

    def __init__(self, process, pipeline, definition):
        super().__init__(process, pipeline, definition)
        sharding = dict(definition.sharding or {})
        if sharding:
            # "devices": [start, end) pins this element to a mesh
            # SUB-SLICE -- pipeline stages partition the pod (stage-level
            # pipeline parallelism, SURVEY.md 2.4 PP equivalent)
            devices = None
            device_range = sharding.get("devices")
            if device_range:
                start, end = int(device_range[0]), int(device_range[1])
                devices = jax.devices()[start:end]
            self.mesh = get_mesh(sharding.get("axes"), devices)
        else:
            self.mesh = None
        self._state_spec = sharding.get("state")
        self._input_specs = dict(sharding.get("inputs", {}))
        self._bucket_axes = dict(
            self.get_parameter("bucket_axes", {}) or {})
        self._bucket_min = int(self.get_parameter("bucket_min", 16))
        self._buckets = self.get_parameter("buckets", None)
        self._unpad_outputs = bool(
            self.get_parameter("unpad_outputs", True))
        self._blocking_metrics = bool(
            self.get_parameter("blocking_metrics", False))
        self.state = None
        self._compiled = None
        self._accepts_lengths = False
        self._replicated_warned: set = set()
        self._group_kernel_fn = None

    # -- the compute contract (override these) -----------------------------

    def setup(self):
        """Build the element's device state (params pytree); called lazily
        before the first frame.  Return None for stateless elements."""
        return None

    def compute(self, state, **inputs) -> dict:
        """PURE function: jax in, jax out.  No side effects, no Python
        branching on traced values."""
        raise NotImplementedError

    def dynamic_parameters(self, stream: Stream) -> dict:
        """Per-frame parameter values to pass as TRACED kwargs to compute.
        Read get_parameter(...) here (not inside compute) for live-updatable
        values: they enter the compiled fn as 0-d arrays, so updates apply
        without recompilation."""
        return {}

    # -- engine ------------------------------------------------------------

    def configure(self) -> None:
        """Idempotent pre-state configuration hook: build self.config /
        default self._state_spec here (NOT in setup) so the checkpoint
        RESTORE path -- which installs state without calling setup() --
        still configures the element before sharding or compute."""

    def _ensure_ready(self):
        if self._compiled is not None:
            return
        self.configure()
        if self.state is None:  # restore_state may have installed it
            state = self.setup()
            if state is not None and self.mesh is not None:
                state = shard_pytree(state, self.mesh, self._state_spec)
            self.state = state
        signature = inspect.signature(self.compute)
        self._accepts_lengths = "lengths" in signature.parameters

        def _call(state, dynamic, kwargs):
            outputs = self.compute(state, **dynamic, **kwargs)
            if not isinstance(outputs, dict):
                raise TypeError(
                    f"{self.definition.name}.compute must return a dict")
            return outputs

        self._compiled = jax.jit(_call)

    def _place_inputs(self, inputs: dict) -> tuple[dict, dict]:
        """Returns (placed inputs, padding info {name: (axis, original)})."""
        placed, padding = {}, {}
        for name, value in inputs.items():
            if isinstance(value, (np.ndarray, jnp.ndarray)) or hasattr(
                    value, "__jax_array__"):
                axis = self._bucket_axes.get(name)
                if axis is not None:
                    original = value.shape[int(axis)]
                    target = bucket_length(
                        original, self._bucket_min, self._buckets)
                    if target != original:
                        value = pad_axis_to(value, int(axis), target)
                        padding[name] = (int(axis), original)
                spec = self._input_specs.get(name)
                if self.mesh is not None and spec is not None:
                    sharding = named_sharding(self.mesh, spec)
                    try:
                        sharding.shard_shape(tuple(value.shape))
                    except ValueError:
                        # dim not divisible by its mesh axis: replicate
                        # rather than fail the frame -- but say so, this
                        # forfeits the parallelism the definition asked for
                        if name not in self._replicated_warned:
                            self._replicated_warned.add(name)
                            _LOGGER.warning(
                                "%s: input '%s' shape %s not divisible by "
                                "mesh axes %s; running REPLICATED",
                                self.definition.name, name,
                                tuple(value.shape), sharding.spec)
                        value = jnp.asarray(value)
                    else:
                        value = jax.device_put(value, sharding)
                elif isinstance(value, np.ndarray):
                    value = jnp.asarray(value)
            placed[name] = value
        return placed, padding

    def _unpad(self, outputs: dict, inputs: dict, padding: dict) -> dict:
        """Slice bucket padding back off: any output array whose bucketed
        axis has exactly the padded input's size is restored to the
        original length (opt out with unpad_outputs=false)."""
        if not padding or not self._unpad_outputs:
            return outputs
        result = {}
        for name, value in outputs.items():
            # every padded axis is restored (an output may carry several
            # bucketed axes, e.g. an outer product of two padded inputs)
            sliced_axes: set = set()
            for input_name, (axis, original) in padding.items():
                padded_size = inputs[input_name].shape[axis]
                if (hasattr(value, "shape") and value.ndim > axis
                        and axis not in sliced_axes
                        and value.shape[axis] == padded_size):
                    index = [slice(None)] * value.ndim
                    index[axis] = slice(0, original)
                    value = value[tuple(index)]
                    sliced_axes.add(axis)
            result[name] = value
        return result

    def group_kernel(self, stream: Stream):
        """Fused whole-group execution for free: compute() exposed as a
        batch-in/batch-out kernel so the micro-batch scheduler traces
        concat+pad+compute+split as ONE program (PipelineElement
        .group_kernel contract).  State and dynamic parameters ride the
        traced `context` -- never baked-in constants -- so checkpoint
        restores and live parameter updates apply without a stale
        executable.  Elements whose engine path does host-side per-frame
        work fall back to the chained path: bucket padding and `lengths`
        masks depend on pre-padding sizes, meshed inputs need NamedSharding
        placement, blocking_metrics promises an in-window
        block_until_ready, and a custom process_frame override means
        compute() alone would not reproduce the element's behavior."""
        if (self._bucket_axes or self.mesh is not None
                or self._blocking_metrics):
            return None
        if (type(self).process_frame is not ComputeElement.process_frame
                or type(self).compute is ComputeElement.compute):
            return None
        self._ensure_ready()
        if self._accepts_lengths:
            return None
        if self._group_kernel_fn is None:
            def kernel(context, **batch):
                state, dynamic = context
                outputs = self.compute(state, **dynamic, **batch)
                if not isinstance(outputs, dict):
                    raise TypeError(
                        f"{self.definition.name}.compute must return "
                        f"a dict")
                return outputs

            self._group_kernel_fn = kernel
        dynamic = {
            key: jnp.asarray(value)
            for key, value in self.dynamic_parameters(stream).items()}
        return self._group_kernel_fn, (self.state, dynamic)

    def eval_kernel(self):
        """Abstract-interpretation hook for the static analyzer
        (PipelineElement.eval_kernel contract): compute() exposed with
        its state BUILDER so the analyzer can dry-run
        setup-then-compute entirely under jax.eval_shape -- no
        parameter allocation, no compile, no device.  Elements whose
        engine path depends on runtime sizes (bucket padding, `lengths`
        masks) or a custom process_frame fall out: compute() alone
        would not reproduce their behavior."""
        if (type(self).compute is ComputeElement.compute
                or type(self).process_frame
                is not ComputeElement.process_frame):
            return None
        if self._bucket_axes or "lengths" in inspect.signature(
                self.compute).parameters:
            return None
        self.configure()

        def kernel(state, **batch):
            dynamic = {
                key: jnp.asarray(value)
                for key, value in self.dynamic_parameters(None).items()}
            return self.compute(state, **dynamic, **batch)

        return kernel, self.setup

    def _cached_group_kernel(self, key, build):
        """Per-static-parameter-value kernel cache for group_kernel
        overrides (e.g. one kernel per max_tokens): a STABLE kernel
        identity per value keeps the scheduler's compiled fused program
        (and every executable under it) cached across groups."""
        kernels = getattr(self, "_group_kernels", None)
        if kernels is None:
            kernels = self._group_kernels = {}
        kernel = kernels.get(key)
        if kernel is None:
            kernel = kernels[key] = build()
        return kernel

    def restore_state(self, state) -> None:
        """Install checkpointed state (numpy pytree from Checkpointer),
        re-placing it on the element's mesh.  Installed BEFORE
        _ensure_ready so setup() never allocates a fresh params pytree
        that would double peak HBM on the restore path."""
        self.configure()  # state specs / config must exist before placing
        if state is not None:
            if self.mesh is not None:
                state = shard_pytree(state, self.mesh, self._state_spec)
            else:
                state = jax.tree_util.tree_map(jnp.asarray, state)
            self.state = state
        self._ensure_ready()

    def process_frame(self, stream: Stream, **inputs) -> tuple:
        self._ensure_ready()
        host_start = time.perf_counter()
        placed, padding = self._place_inputs(inputs)
        dynamic = {
            key: jnp.asarray(value)
            for key, value in self.dynamic_parameters(stream).items()}
        if self._accepts_lengths:
            dynamic["lengths"] = {
                name: jnp.int32(inputs[name].shape[int(axis)])
                for name, axis in self._bucket_axes.items()
                if name in inputs}
        try:
            # TraceAnnotation: per-element spans in jax.profiler traces
            # (SURVEY.md section 5 tracing parity).  The element's mesh
            # becomes the AMBIENT mesh for the compiled call, so compute
            # bodies may use shard_map collectives with mesh=None (ring
            # attention, sp decode -- the long-context path).
            mesh_scope = (jax.set_mesh(self.mesh)
                          if self.mesh is not None
                          else contextlib.nullcontext())
            with mesh_scope, jax.profiler.TraceAnnotation(
                    f"element:{self.definition.name}"):
                outputs = self._compiled(self.state, dynamic, placed)
        except TypeError as error:
            bad = {name: type(value).__name__
                   for name, value in placed.items()
                   if not hasattr(value, "shape")
                   and not isinstance(value, (bool, int, float, complex,
                                              list, tuple))}
            if bad:
                raise TypeError(
                    f"{self.definition.name}: inputs {bad} are not JAX "
                    f"types; ComputeElement inputs must be arrays or "
                    f"numbers (route strings/objects around compute "
                    f"elements with map_in/map_out)") from error
            raise
        outputs = self._unpad(outputs, placed, padding)
        block_elapsed = None
        if self._blocking_metrics:
            block_start = time.perf_counter()
            outputs = jax.block_until_ready(outputs)
            block_elapsed = time.perf_counter() - block_start
        elapsed = time.perf_counter() - host_start
        telemetry = getattr(self.pipeline, "telemetry", None)
        if telemetry is None or telemetry.enabled:
            stream.variables.setdefault("compute_seconds", {})[
                self.definition.name] = elapsed
        if telemetry is not None:
            telemetry.record_device(self.definition.name, elapsed,
                                    block_elapsed)
        return StreamEvent.OKAY, outputs
