# Pipeline engine: executes dataflow graphs of PipelineElements over
# streams of frames.
#
# Capability parity with the reference pipeline engine (reference:
# src/aiko_services/main/pipeline.py:522-1283): graph construction from the
# definition (local elements loaded by module/class, remote elements
# discovered by service filter), stream lifecycle with grace-time leases,
# per-frame execution in topological order with map_in/map_out name mapping,
# per-element wall-clock metrics, StreamEvent policy (ERROR destroys the
# stream, STOP destroys gracefully, DROP_FRAME skips the rest of the graph),
# remote element pause/resume (frame pauses at the remote node, resumes via
# Graph.iterate_after on process_frame_response, reference
# pipeline.py:1083-1160), the auto-created "*" default stream, response
# routing (local queue | response topic | /out), and live parameter updates.
#
# TPU-first differences: swag values stay on device (jax.Array) in-process;
# cross-process hops use the tensor codec; stream context is explicit (no
# thread-locals); the event engine dispatches with microsecond latency.

from __future__ import annotations

import hashlib
import json
import logging
import time
import traceback
from collections import deque

from ..faults import create_injector, get_injector
from ..observe import PipelineTelemetry
from ..observe.trace import pop_trace_context
from ..runtime import Actor, Lease, ServiceFilter, ServicesCache
from ..runtime.service import SERVICE_PROTOCOL_PIPELINE
from ..utils import (
    generate, get_logger, load_module, parse_float, parse_int)
from ..utils.padding import bucket_length, pad_axis_to
from .definition import (
    PipelineDefinition, parse_pipeline_definition,
    validate_pipeline_definition)
from .element import AsyncHostElement, PipelineElement
from .stream import (
    DEFAULT_STREAM_ID, Frame, Stream, StreamEvent, StreamState)
from .tensors import decode_frame_data, encode_frame_data

__all__ = ["Pipeline", "RemoteElement", "create_pipeline"]

_LOGGER = get_logger("pipeline")
DEFAULT_GRACE_TIME = 60.0
# error-budget defaults: disabled unless `error_budget` is declared
# (stream or pipeline parameter); the window is seconds
DEFAULT_ERROR_WINDOW = 10.0
# a fused group program failing this many CONSECUTIVE times at RUN
# time pins the element to the chained path permanently (a flapping
# kernel must not pay fused-failure + chained-retry on every group; a
# healthy fused group in between resets the count)
FUSED_FLAP_LIMIT = 3
# dead-letter diagnostics are truncated: the topic carries evidence,
# not payloads
_DEAD_LETTER_DIAGNOSTIC_CAP = 500
# dead letters embed the ENCODED inputs when they fit under this cap
# (AIKO_DEAD_LETTER_DATA_MAX chars), so `aiko deadletter replay` can
# re-submit the exact frame after a recovered outage; oversized frames
# keep the descriptor-only shape (evidence, not payload)
_DEAD_LETTER_DATA_CAP = 4096


def _diagnostic_of(outputs) -> str:
    """An element's ERROR payload is not guaranteed to be a dict --
    _safe_call only validates the StreamEvent half of the tuple, so
    (StreamEvent.ERROR, "text") reaches the error handlers intact."""
    if isinstance(outputs, dict):
        return str(outputs.get("diagnostic") or outputs)
    return str(outputs)


def _canonical_value(value):
    """Hashable canonical encoding for parameter fingerprints: dict
    order never matters, arrays compare by CONTENT (shape + dtype +
    digest of the bytes, never a truncating repr), unknown types fall
    back to type-tagged repr.  Two values encode equal iff a coalesced
    element resolving either would behave identically."""
    if hasattr(value, "shape") and hasattr(value, "dtype"):
        import numpy as np
        array = np.asarray(value)
        return ("nd", array.shape, str(array.dtype),
                hashlib.blake2b(array.tobytes(),
                                digest_size=16).digest())
    if isinstance(value, dict):
        return ("d", tuple(sorted(
            (str(key), _canonical_value(item))
            for key, item in value.items())))
    if isinstance(value, (list, tuple)):
        return ("l", tuple(_canonical_value(item) for item in value))
    if isinstance(value, (str, int, float, bool, bytes, type(None))):
        # type-tagged: Python cross-type equality (True == 1 == 1.0)
        # must not let type-distinct values fingerprint equal -- an
        # element branching on isinstance/dtype would silently take the
        # lead stream's path
        return ("s", type(value).__name__, value)
    return ("r", type(value).__name__, repr(value))

_SPLIT_JIT = None
_COALESCE_JIT = None


def _concat_pad(named: dict, target: int) -> dict:
    """Concat each input's per-frame arrays on axis 0 and pad to
    `target` rows.  The ONE definition of the coalesce math: the
    standalone jitted program (chained path) and the fused group
    program both trace THIS function, so the fused==chained
    equivalence can never drift."""
    import jax.numpy as jnp
    out = {}
    for name, arrays in named.items():
        value = (arrays[0] if len(arrays) == 1
                 else jnp.concatenate(arrays, axis=0))
        out[name] = pad_axis_to(value, 0, target)
    return out


def _concat_pad_program(named_arrays: dict, target: int):
    """_concat_pad as ONE compiled program.  The eager concatenate this
    replaces cost ~40 ms of tunnel dispatch PER GROUP on the tunneled
    TPU (measured round 5: 310 frames/s eager vs 1 403 jitted on the
    yolov8n serving chain), swamping the coalesced call it was
    feeding.  jit caches one executable per (names, arity, shapes)
    signature; the caller keeps arity stable by padding the entry list
    with fillers."""
    global _COALESCE_JIT
    if _COALESCE_JIT is None:
        import functools

        import jax

        _COALESCE_JIT = functools.partial(
            jax.jit, static_argnames=("target",))(_concat_pad)
    return _COALESCE_JIT(named_arrays, target)


def _split_leaves_program(leaves: tuple, counts: tuple):
    """All per-frame row slices of all device leaves as ONE device
    program: returns frames x leaves nested tuples.  jit caches one
    executable per (leaf shapes, counts) combination."""
    global _SPLIT_JIT
    if _SPLIT_JIT is None:
        import functools

        import jax

        @functools.partial(jax.jit, static_argnames=("counts",))
        def split(leaves, counts):
            frames = []
            offset = 0
            for count in counts:
                frames.append(tuple(
                    leaf[offset:offset + count] for leaf in leaves))
                offset += count
            return tuple(frames)

        _SPLIT_JIT = split
    return _SPLIT_JIT(leaves, counts=counts)


class RemoteElement:
    """Proxy node for an element hosted by another pipeline service
    (reference PipelineRemote, pipeline.py:1285-1319)."""

    def __init__(self, pipeline, definition):
        self.pipeline = pipeline
        self.definition = definition
        self.name = definition.name
        self.ready = False
        self.topic_path = None
        self._pending: list[str] = []

    def set_remote(self, topic_path: str) -> None:
        self.topic_path = topic_path
        self.ready = True
        pending, self._pending = self._pending, []
        for payload in pending:
            self.pipeline.process.publish(f"{topic_path}/in", payload)
        self.pipeline._update_lifecycle()

    def set_absent(self) -> None:
        self.ready = False
        self.topic_path = None
        self.pipeline._update_lifecycle()

    def call(self, command: str, parameters) -> None:
        payload = generate(command, parameters)
        if self.ready:
            self.pipeline.process.publish(f"{self.topic_path}/in", payload)
        else:
            self._pending.append(payload)


class Pipeline(Actor):
    def __init__(self, process, definition: PipelineDefinition,
                 name: str = None):
        super().__init__(process, name or definition.name,
                         protocol=SERVICE_PROTOCOL_PIPELINE)
        self.definition = definition
        self.graph = validate_pipeline_definition(definition)
        self.streams: dict[str, Stream] = {}
        self._stream_leases: dict[str, Lease] = {}
        self._frame_count = 0
        self.elements: dict[str, object] = {}
        self._services_cache: ServicesCache | None = None
        self._remote_handlers: list = []
        # micro-batching: frames parked PER ELEMENT awaiting a coalesced
        # flush -- across streams, so the many-stream serving scenario
        # batches (SURVEY.md section 7 hard-part #2: batching scheduler
        # that still honors StreamEvent semantics).  Entries are
        # (stream, frame, inputs, signature)
        self._micro_pending: dict[str, list] = {}
        # zero-filler buffers reused across coalesced groups (immutable
        # device arrays; a fresh zeros_like per group is a dispatch)
        self._micro_fillers: dict[tuple, object] = {}
        # fused whole-group programs: node -> {kernel id: (kernel,
        # jitted concat+pad+kernel+split)}; jit caches one executable
        # per (input names, arity, shapes) signature underneath.  A
        # DICT per node, not one slot: elements cache one kernel per
        # static parameter value (max_new_tokens, max_tokens), and
        # alternating cohorts must not evict each other's compiled
        # programs (a rebuild discards every XLA executable under it)
        self._fused_programs: dict[str, dict] = {}
        self._fused_rejected: set = set()
        # fused-path circuit breaker: RUN-time program failures per node;
        # FUSED_FLAP_LIMIT failures pin the node to the chained path
        self._fused_failures: dict[str, int] = {}
        self._fused_disabled: set = set()
        # deterministic fault injection (aiko_services_tpu.faults): the
        # pipeline parameter `faults` takes precedence, else the
        # process-wide AIKO_FAULTS plan; None (the production state)
        # keeps every hook at one is-None check
        fault_spec = (definition.parameters or {}).get("faults")
        self.faults = (create_injector(fault_spec) if fault_spec
                       else get_injector())
        # elements whose parked frames split into parameter-fingerprint
        # cohorts, logged once each (operators see WHY cross-stream
        # coalescing produced small groups)
        self._micro_cohort_logged: set = set()
        # open hold-down windows: node -> timer fn (see
        # _schedule_micro_flush); generations invalidate STALE posted
        # flush messages from superseded windows
        self._micro_timers: dict[str, object] = {}
        self._micro_flush_gen: dict[str, int] = {}
        self.share.update({
            "definition_name": definition.name,
            "element_count": len(definition.elements),
            "stream_count": 0,
            "frame_count": 0,
        })
        # disaggregated serving: a `disagg: "role=prefill"` definition
        # parameter pins this replica's pool; the `role` share key is
        # how a discovering gateway learns pool membership (local
        # attaches read it directly).  Parse errors are left to the
        # construction lint (AIKO408) below
        disagg_spec = (definition.parameters or {}).get("disagg")
        if disagg_spec:
            from ..serve.disagg import DisaggPolicy
            try:
                disagg_role = DisaggPolicy.parse(disagg_spec).role
            except ValueError:
                disagg_role = None
            if disagg_role:
                self.share["role"] = disagg_role
        # telemetry: metrics registry + frame tracer + periodic export
        # (pipeline parameter "telemetry: false" disables ALL per-frame
        # instrument writes -- the latency operating point)
        self.telemetry = PipelineTelemetry(self)
        # definition-time static analysis (analyze/): the cheap passes
        # (graph/port dataflow, tensor-spec flow, policy grammars) run
        # at construction so a shape clash or typo'd grammar fails HERE
        # with a rule code, not mid-stream as a dead-letter.  Opt out
        # with pipeline parameter `validate: false`; error findings
        # raise, warnings are logged and exported through the metrics
        # registry (`lint.findings` + per-rule counters)
        from ..utils import truthy
        if truthy((definition.parameters or {}).get("validate", True)):
            self._run_construction_lint(definition)
        self._produced_keys = self._compute_produced_keys()
        self._create_elements()
        self._update_lifecycle()

    # -- construction ------------------------------------------------------

    def _run_construction_lint(self, definition) -> None:
        """The analyzer's cheap passes at construction: error findings
        raise DefinitionError (the definition is wrong); warnings are
        admitted but logged and counted through the metrics registry so
        fleets can see how many definitions carry findings."""
        from ..analyze import CHEAP_PASSES, analyze_definition
        # re-runs the graph pass validate_pipeline_definition already
        # ran: deliberate -- the passes are pure and run in
        # microseconds, and sharing the report would couple the
        # engine's unconditional structural validation to the
        # opt-out-able lint surface
        report = analyze_definition(definition, passes=CHEAP_PASSES)
        errors = report.errors()
        if errors:
            from .definition import DefinitionError
            raise DefinitionError(
                f"{definition.name}: definition rejected by static "
                "analysis (`validate: false` opts out):\n"
                + "\n".join(d.render() for d in errors))
        self.telemetry.record_lint(report)
        for diagnostic in report.findings:
            _LOGGER.warning("%s: lint: %s", self.name,
                            diagnostic.render())

    def _compute_produced_keys(self) -> set:
        produced = set()
        for element_definition in self.definition.elements:
            for output_name in element_definition.output_names():
                produced.add(element_definition.map_out.get(
                    output_name, output_name))
        return produced

    def _create_elements(self) -> None:
        for element_definition in self.definition.elements:
            if element_definition.is_local:
                module = load_module(element_definition.deploy_local["module"])
                element_class = getattr(
                    module, element_definition.deploy_local["class_name"])
                if not issubclass(element_class, PipelineElement):
                    raise TypeError(
                        f"{element_definition.name}: "
                        f"{element_class.__name__} is not a PipelineElement")
                element = element_class(
                    self.process, self, element_definition)
                if isinstance(element, AsyncHostElement) and (
                        type(element).group_kernel
                        is not PipelineElement.group_kernel):
                    raise TypeError(
                        f"{element_definition.name}: AsyncHostElement "
                        f"cannot expose a group kernel -- its work runs "
                        f"on a host worker thread (device readbacks, "
                        f"blocking I/O) and cannot trace into a fused "
                        f"device program; drop group_kernel or use a "
                        f"ComputeElement")
                self.elements[element_definition.name] = element
            else:
                remote = RemoteElement(self, element_definition)
                self.elements[element_definition.name] = remote
                self._watch_remote(remote)

    def _watch_remote(self, remote: RemoteElement) -> None:
        if self._services_cache is None:
            from ..runtime.share import services_cache_create_singleton
            self._services_cache = services_cache_create_singleton(
                self.process)
        service_filter = ServiceFilter(
            **remote.definition.deploy_remote["service_filter"])

        def handler(command, fields):
            if command == "add" and not remote.ready:
                remote.set_remote(fields.topic_path)
            elif command == "remove" and fields.topic_path == (
                    remote.topic_path):
                remote.set_absent()

        self._services_cache.add_handler(handler, service_filter)
        self._remote_handlers.append(handler)

    def _update_lifecycle(self) -> None:
        ready = all(
            not isinstance(element, RemoteElement) or element.ready
            for element in self.elements.values())
        lifecycle = "ready" if ready else "waiting_remote"
        if self.ec_producer is not None:
            self.ec_producer.update("lifecycle", lifecycle)
        else:
            self.share["lifecycle"] = lifecycle

    @property
    def ready(self) -> bool:
        return self.share.get("lifecycle") == "ready"

    # -- stream lifecycle --------------------------------------------------

    def create_stream(self, stream_id, parameters=None,
                      grace_time=DEFAULT_GRACE_TIME, topic_response=None,
                      queue_response=None, graph_path=None,
                      first_frame_id: int = 0) -> Stream | None:
        stream_id = str(stream_id)
        if stream_id in self.streams:
            existing = self.streams[stream_id]
            if isinstance(parameters, str):
                try:
                    parameters = (json.loads(parameters)
                                  if parameters else {})
                except ValueError:
                    parameters = None
            if parameters and dict(parameters) != existing.parameters:
                # the caller gets the EXISTING stream, configured under
                # the FIRST parameter set -- silent reuse here has
                # masked id-allocation bugs (two clients minting the
                # same id with different configs); name both sets so
                # the losing caller's missing knobs are attributable
                _LOGGER.warning(
                    "%s: create_stream(%s) collided with a live stream;"
                    " keeping existing parameters %r, ignoring %r",
                    self.name, stream_id, existing.parameters,
                    dict(parameters))
                self.telemetry.record_stream_collision(stream_id)
            return existing
        try:
            if isinstance(parameters, str):  # wire call: JSON-encoded
                parameters = json.loads(parameters) if parameters else {}
            if isinstance(grace_time, str):
                grace_time = float(grace_time)
        except ValueError as error:
            _LOGGER.warning("%s: bad create_stream arguments: %s",
                            self.name, error)
            return None
        # wire placeholders: the sexpr codec renders None as an empty
        # list, so positional wire calls (e.g. the serving gateway's
        # create_stream with first_frame_id) deliver [] for the slots
        # they skip -- a falsy responder/path means "not provided"
        if not queue_response:
            queue_response = None
        if not graph_path:
            graph_path = None
        if graph_path and str(graph_path) not in self.graph:
            # validate BEFORE registering: a bad head must not leave a
            # half-created stream holding a lease
            _LOGGER.warning("%s: unknown graph_path %r for stream %s",
                            self.name, graph_path, stream_id)
            return None
        stream = Stream(
            stream_id=stream_id, parameters=parameters or {},
            topic_response=topic_response or None,
            queue_response=queue_response, graph_path=graph_path)
        # cursor must be set BEFORE start_stream: DataSources may begin
        # generating frames the moment they start (checkpoint resume)
        stream.frame_id = int(first_frame_id)
        self.streams[stream_id] = stream
        self._stream_leases[stream_id] = Lease(
            self.process.event, grace_time, stream_id,
            lease_expired_handler=self._stream_lease_expired,
            jitter=self._lease_jitter(stream_id))
        # Remote streams FIRST: a local DataSource may start generating
        # frames the moment start_stream returns, and those frames must not
        # reach a remote pipeline before its create_stream does.
        for node_name in self.graph.get_path(stream.graph_path):
            element = self.elements[node_name]
            if isinstance(element, RemoteElement):
                element.call("create_stream", [
                    stream_id,
                    json.dumps(stream.parameters).encode("ascii"),
                    grace_time,
                    self.topic_in,
                ])
        for node_name in self.graph.get_path(stream.graph_path):
            element = self.elements[node_name]
            if not isinstance(element, RemoteElement):
                stream_event, diagnostic = self._safe_call(
                    node_name, element.start_stream, stream, stream_id)
                if stream_event == StreamEvent.ERROR:
                    _LOGGER.error("%s: start_stream failed at %s: %s",
                                  self.name, node_name, diagnostic)
                    self.destroy_stream(stream_id, state=StreamState.ERROR)
                    return None
        self._update_stream_share()
        return stream

    def destroy_stream(self, stream_id,
                       state: StreamState = StreamState.STOP,
                       graceful=False) -> None:
        stream_id = str(stream_id)
        if isinstance(state, str):  # wire call
            state = StreamState(state)
        if isinstance(graceful, str):
            graceful = graceful.lower() == "true"
        stream = self.streams.get(stream_id)
        if stream is None:
            return
        if graceful and stream.pending > 0:
            # defer until in-flight frames finish (reference graceful STOP,
            # pipeline.py:1229-1263)
            stream.stop_requested = True
            return
        if stream.destroying:
            return
        stream.destroying = True
        stream.state = state
        # parked frames die with the stream (other streams' entries stay)
        for node_name, entries in list(self._micro_pending.items()):
            kept = [entry for entry in entries
                    if entry[0].stream_id != stream_id]
            if kept:
                self._micro_pending[node_name] = kept
            else:
                self._micro_pending.pop(node_name, None)
        lease = self._stream_leases.pop(stream_id, None)
        if lease is not None:
            lease.terminate()
        for node_name in self.graph.get_path(stream.graph_path):
            element = self.elements[node_name]
            if isinstance(element, RemoteElement):
                element.call("destroy_stream", [stream_id])
            else:
                element.stop_frame_generation(stream_id)
                self._safe_call(node_name, element.stop_stream, stream,
                                stream_id)
        # pop LAST: "stream gone from pipeline.streams" must imply the
        # stop_stream hooks (writer close/flush) have already run --
        # callers synchronize on stream removal
        self.streams.pop(stream_id, None)
        self._update_stream_share()

    def _lease_jitter(self, stream_id: str) -> float:
        """Deterministic per-stream timer jitter decorrelating
        stream-lease expiry checks (thousands of streams created in one
        burst must not tick in lockstep).  Seeded by the fault harness
        (its seed, else 0) so fault-scenario runs reproduce the exact
        timer schedule."""
        from ..runtime.lease import jitter_fraction
        seed = self.faults.seed if self.faults is not None else 0
        return jitter_fraction(seed, stream_id)

    def _stream_lease_expired(self, stream_id) -> None:
        _LOGGER.info("%s: stream %s lease expired", self.name, stream_id)
        self._stream_leases.pop(str(stream_id), None)
        self.destroy_stream(stream_id)

    # -- frame execution ---------------------------------------------------

    def create_frame(self, stream: Stream, frame_data: dict) -> None:
        """Inject a frame locally (element thread or event loop): posts onto
        the pipeline mailbox to preserve actor ordering."""
        stream.pending += 1
        self.post_message(
            "process_frame",
            [{"stream_id": stream.stream_id, "_local": True}, frame_data])

    def process_frame(self, stream_dict, frame_data=None) -> None:
        try:
            if isinstance(stream_dict, str):
                stream_dict = json.loads(stream_dict)
            if isinstance(frame_data, str):
                frame_data = decode_frame_data(frame_data)
        except (ValueError, KeyError) as error:
            _LOGGER.warning("%s: undecodable frame dropped: %s",
                            self.name, error)
            return
        frame_data = frame_data or {}
        stream_id = str(stream_dict.get("stream_id", DEFAULT_STREAM_ID))
        stream = self.streams.get(stream_id)
        if stream is None:
            if stream_id == DEFAULT_STREAM_ID:
                # auto-create the default stream (reference
                # pipeline.py:1131-1137)
                stream = self.create_stream(stream_id)
            if stream is None:
                _LOGGER.debug("%s: frame for unknown stream %s dropped",
                              self.name, stream_id)
                return
        lease = self._stream_leases.get(stream_id)
        if lease is not None:
            lease.extend()
        frame_id = stream_dict.get("frame_id")
        if frame_id is None:
            frame_id = stream.frame_id
        frame_id = int(frame_id)
        if frame_id >= stream.frame_id:
            stream.frame_id = frame_id + 1
        topic_response = stream_dict.get("topic_response")
        if topic_response:  # remote caller overrides response routing
            stream.topic_response = topic_response
        if not stream_dict.get("_local"):
            stream.pending += 1
        # a propagated trace context (serving gateway = root-span
        # owner) rides the frame data under a reserved key: pop it at
        # ingress so it NEVER reaches element inputs, then continue the
        # upstream trace instead of minting a fresh id
        trace_context = pop_trace_context(frame_data)
        frame = Frame(frame_id=frame_id, swag=dict(frame_data))
        stream.frames[frame_id] = frame
        # stream ingress: mint the frame's trace id (spans accumulate on
        # the frame as it moves through the graph)
        self.telemetry.frame_begin(stream, frame, context=trace_context)
        # frame deadline: bounds the WHOLE graph walk including parked
        # remote/async branches -- a dead RemoteElement or lost reply
        # releases the frame (dead-lettered) instead of leaking it until
        # the stream lease expires
        deadline = self._frame_deadline(stream)
        if deadline > 0:
            self._arm_frame_deadline(stream, frame, deadline)
        self._run_frame(stream, frame, resume_after=None)

    def process_frame_response(self, stream_dict, frame_data=None) -> None:
        """A remote element (hosted sub-pipeline) replied: resume the paused
        frame after the remote node (reference pipeline.py:1156-1160)."""
        try:
            if isinstance(stream_dict, str):
                stream_dict = json.loads(stream_dict)
        except ValueError as error:
            _LOGGER.warning("%s: undecodable frame response dropped: %s",
                            self.name, error)
            return
        stream_id = str(stream_dict.get("stream_id", DEFAULT_STREAM_ID))
        stream = self.streams.get(stream_id)
        if stream is None:
            _LOGGER.debug("%s: response for unknown stream %s",
                          self.name, stream_id)
            return
        frame_id = int(stream_dict.get("frame_id", 0))
        frame = stream.frames.get(frame_id)
        if frame is None or (frame.paused_pe_name is None
                             and not frame.pending_nodes):
            _LOGGER.debug("%s: response for unknown frame %s/%s",
                          self.name, stream_id, frame_id)
            return
        # concurrent branches: responses name their node.  An UN-NAMED
        # response can only originate from a remote hop (the reply
        # protocol carries no node) or a CUSTOM PENDING element --
        # AsyncHostElement replies always name their node, and
        # micro-batch parks resume via the flush path, so neither is a
        # candidate for un-named attribution
        resumed_node = stream_dict.get("node")
        if not resumed_node:
            holder = frame.paused_pe_name
            holder_is_remote = isinstance(
                self.elements.get(holder), RemoteElement)
            nameless_capable = [
                node for node in frame.pending_nodes
                if not isinstance(self.elements.get(node),
                                  (AsyncHostElement, RemoteElement))
                and not any(entry[1] is frame
                            for entry in self._micro_pending.get(
                                node, ()))]
            if holder is not None and holder_is_remote:
                resumed_node = holder   # remote replies are un-named
            elif (len(nameless_capable) == 1
                    and not frame.had_remote_park):
                # exactly one park can have sent this, and no remote hop
                # ever touched the frame (so it cannot be a delayed
                # duplicate of a remote reply): unambiguous
                resumed_node = nameless_capable[0]
            elif not nameless_capable:
                # no park can have sent an un-named reply: stale or
                # duplicate -- falls through to the drop below (in-flight
                # async branches keep the frame alive and healthy)
                resumed_node = None
            else:
                # several nameless parks (or a possible remote-reply
                # duplicate): attribution would be a guess.  Don't kill
                # the frame outright -- arm a watchdog over the doubtful
                # parks instead, so a misbehaving custom PENDING element
                # degrades to a delayed dropped frame rather than
                # permanently holding a backpressure slot, while healthy
                # named branches in flight stay untouched
                _LOGGER.warning(
                    "%s: un-named frame response unroutable over parks "
                    "%s on frame %s/%s (custom elements returning "
                    "PENDING alongside siblings or remote hops must "
                    "name their node in process_frame_response); park "
                    "watchdog armed", self.name,
                    sorted(nameless_capable), stream_id, frame_id)
                self._arm_park_watchdog(stream, frame, nameless_capable)
                return
        if resumed_node is None or (
                resumed_node not in frame.pending_nodes
                and resumed_node != frame.paused_pe_name):
            _LOGGER.debug("%s: response for non-pending node %r on "
                          "frame %s/%s", self.name, resumed_node,
                          stream_id, frame_id)
            return
        if (self.faults is not None
                and self.faults.reply_blackhole(resumed_node)):
            # injected lost reply: the frame stays parked, exactly as a
            # dead remote hop leaves it -- frame_deadline is the
            # recovery path under test
            _LOGGER.warning(
                "%s: injected blackhole swallowed %s response on frame "
                "%s/%s", self.name, resumed_node, stream_id, frame_id)
            return
        if isinstance(frame_data, str):
            try:
                frame_data = decode_frame_data(frame_data)
            except (ValueError, KeyError) as error:
                # payload unrecoverable (e.g. transfer-plane producer
                # died): release the parked frame as an error instead of
                # leaking it until the stream lease expires
                _LOGGER.warning(
                    "%s: frame response payload lost (%s); releasing "
                    "frame %s/%s", self.name, error, stream_id, frame_id)
                self._finish_frame(stream, frame, dropped=True, error=True)
                return
        remote_event = stream_dict.get("event")
        if remote_event:  # remote dropped/errored the frame: release it
            self._finish_frame(stream, frame, dropped=True,
                               error=(remote_event == "error"))
            return
        outputs = frame_data or {}
        element = self.elements.get(resumed_node)
        if element is not None and not isinstance(element, RemoteElement):
            # async LOCAL element: its map_out has not been applied yet
            # (remote hops apply map_out on the serving side)
            outputs = self._map_out(outputs, element.definition)
        elapsed = stream_dict.get("time")
        self.telemetry.mark_resume(
            frame, resumed_node,
            float(elapsed) if elapsed is not None else None,
            path=("remote" if isinstance(element, RemoteElement)
                  else "async"))
        frame.swag.update(outputs)
        frame.pending_nodes.discard(resumed_node)
        if frame.paused_pe_name == resumed_node:
            frame.paused_pe_name = None
        if frame.had_remote_park and not any(
                isinstance(self.elements.get(node), RemoteElement)
                for node in frame.pending_nodes):
            # last remote park resumed: un-named replies can again be
            # attributed to a sole local custom park.  (Residual risk: a
            # transport-redelivered duplicate of the remote's reply
            # arriving after this point could be misrouted -- accepted,
            # since blocking it forever would break every legitimate
            # custom PENDING element downstream of a remote hop)
            frame.had_remote_park = False
        self._run_frame(stream, frame, resume_after=resumed_node)

    def _run_frame(self, stream: Stream, frame: Frame,
                   resume_after: str | None) -> None:
        """One execution pass over the frame's graph path.

        Dependency-aware branch concurrency (beyond the reference's
        strictly sequential loop, pipeline.py:1037-1092): a node whose
        work leaves the event loop (async host element, micro-batch
        park) only defers its own DESCENDANTS -- siblings with satisfied
        inputs keep dispatching, so a slow host readback never idles the
        device behind it.  Each resume event re-enters this pass;
        frame.executed / frame.pending_nodes make passes idempotent.
        Remote hops still park the whole frame (their reply cannot name
        a node)."""
        if resume_after is not None:
            frame.executed.add(resume_after)
        time_start = time.perf_counter()
        for node_name in self.graph.get_path(stream.graph_path):
            if stream.state != StreamState.RUN:
                break
            if (node_name in frame.executed
                    or node_name in frame.pending_nodes):
                continue
            if frame.pending_nodes and any(
                    node_name in self.graph.descendants(pending)
                    for pending in frame.pending_nodes):
                # downstream of an in-flight branch: defer by graph
                # reachability, NOT input availability -- an in-flight
                # element may REWRITE a key this node consumes (e.g.
                # text -> text), so a swag hit here could be the stale
                # pre-branch value
                continue
            stream.current_frame_id = frame.frame_id
            element = self.elements[node_name]
            definition = element.definition
            try:
                inputs = self._map_in(frame.swag, definition)
            except KeyError as error:
                if frame.pending_nodes:
                    # input produced off-path by an in-flight branch
                    # (cross-path key): this node retries on that
                    # branch's resume pass
                    continue
                _LOGGER.error("%s: %s missing input %s",
                              self.name, node_name, error)
                self._finish_frame(stream, frame, error=True)
                return
            if isinstance(element, RemoteElement):
                frame.paused_pe_name = node_name
                frame.pending_nodes.add(node_name)
                frame.had_remote_park = True
                self.telemetry.mark_park(frame, node_name, kind="remote")
                element.call("process_frame", [
                    {"stream_id": stream.stream_id,
                     "frame_id": frame.frame_id,
                     "topic_response": self.topic_in},
                    encode_frame_data(inputs).encode("ascii"),
                ])
                return  # frame stays parked in stream.frames
            park_start = time.perf_counter()
            if self._try_park_micro(stream, frame, node_name, element,
                                    inputs):
                if stream.frames.get(frame.frame_id) is not frame:
                    return  # an inline flush already finished the frame
                # an inline flush ran OTHER frames' passes inside the
                # park call: exclude that window from THIS frame's
                # time_pipeline (each resumed frame charged its own)
                time_start += time.perf_counter() - park_start
                continue  # parked branch; siblings keep dispatching
            element_start = time.perf_counter()
            stream_event, outputs = self._dispatch_element(
                stream, frame, node_name, element, inputs)
            self.telemetry.record_element(
                frame, node_name, element_start,
                time.perf_counter() - element_start, path="inline")
            if stream_event == StreamEvent.OKAY:
                frame.executed.add(node_name)
                frame.swag.update(self._map_out(outputs or {}, definition))
            elif stream_event == StreamEvent.PENDING:
                # element continues off the event loop (AsyncHostElement
                # worker thread); the branch parks and resumes through
                # process_frame_response while siblings continue below.
                # The single fallback-identity slot belongs to remote
                # hops (their replies cannot name a node) -- only claim
                # it when free; AsyncHostElement responses always name
                # their node, and custom PENDING elements must too when
                # combined with remote hops
                if frame.paused_pe_name is None:
                    frame.paused_pe_name = node_name
                frame.pending_nodes.add(node_name)
                self.telemetry.mark_park(frame, node_name, kind="async")
            elif stream_event == StreamEvent.DROP_FRAME:
                self._finish_frame(stream, frame, dropped=True)
                return
            elif stream_event == StreamEvent.STOP:
                _LOGGER.info("%s: %s requested stream stop: %s",
                             self.name, node_name, outputs)
                self._finish_frame(stream, frame)
                self.destroy_stream(stream.stream_id, graceful=True)
                return
            else:  # ERROR or unknown: the element's error policy decides
                if self._handle_element_error(stream, frame, node_name,
                                              element, outputs):
                    continue  # parked for retry; siblings keep dispatching
                return  # frame released (dropped or stream destroyed)
        self.telemetry.record_pipeline_pass(frame, time_start)
        if frame.pending_nodes:
            return  # parked branches resume this pass later
        self._finish_frame(stream, frame)

    # -- fault tolerance ---------------------------------------------------
    # Per-element error policy (`on_error: stop_stream | drop_frame |
    # retry` with max_retries + exponential retry_backoff_ms), a
    # per-stream error budget (`error_budget` errors inside
    # `error_window` seconds quarantines the stream), a per-frame
    # `frame_deadline` covering parked remote/async branches, and
    # dead-lettering of every error-released frame on
    # `{topic_path}/dead_letter` (inputs descriptor + diagnostic +
    # trace id; the Recorder subscribes).  At ROADMAP scale transient
    # faults are the steady state: a single element exception must
    # degrade to one retried/dropped frame, never a destroyed stream,
    # unless the operator kept the stop_stream default.

    def _dispatch_element(self, stream: Stream, frame: Frame,
                          node_name: str, element, inputs: dict) -> tuple:
        """One element call for one frame, with the deterministic fault
        hooks in front (no fault plan -> one is-None check)."""
        faults = self.faults
        if faults is not None:
            delay = faults.dispatch_delay(node_name, frame.frame_id,
                                          stream.stream_id)
            if delay > 0:
                time.sleep(delay)
            if faults.element_raise(node_name, frame.frame_id,
                                    stream.stream_id):
                return StreamEvent.ERROR, {
                    "node": node_name,
                    "diagnostic": f"{node_name}: injected fault "
                                  f"(element_raise frame "
                                  f"{frame.frame_id})"}
        return self._safe_call(node_name, element.process_frame,
                               stream, **inputs)

    def _handle_element_error(self, stream: Stream, frame: Frame,
                              node_name: str, element, outputs) -> bool:
        """Apply the element's error policy to one failed frame.
        Returns True when the frame is still alive (parked for retry) --
        the caller's graph pass may keep dispatching siblings -- and
        False when the frame was released (dropped or stream
        destroyed)."""
        diagnostic = _diagnostic_of(outputs)
        policy = element.resolve_error_policy(stream)
        if policy.on_error == "retry":
            retries = frame.retries
            if retries is None:
                retries = frame.retries = {}
            attempt = retries.get(node_name, 0) + 1
            if attempt <= policy.max_retries:
                retries[node_name] = attempt
                delay = policy.retry_delay(attempt)
                _LOGGER.warning(
                    "%s: %s failed on frame %s/%s (attempt %d/%d), "
                    "retrying in %.0f ms: %s", self.name, node_name,
                    stream.stream_id, frame.frame_id, attempt,
                    policy.max_retries, delay * 1000, diagnostic)
                self.telemetry.record_retry(frame, node_name, attempt,
                                            delay)
                # park while the backoff runs: descendants defer, the
                # frame cannot finish, and the retry message re-enters
                # the graph pass with the node eligible again
                frame.pending_nodes.add(node_name)
                if delay > 0:
                    self.post_message_later(
                        "_retry_element",
                        [stream.stream_id, frame.frame_id, node_name],
                        delay)
                else:
                    self.post_message(
                        "_retry_element",
                        [stream.stream_id, frame.frame_id, node_name])
                return True
        budget_tripped = self._note_stream_error(stream)
        if policy.on_error in ("retry", "drop_frame"):
            reason = ("retries_exhausted" if policy.on_error == "retry"
                      else "drop_frame")
            _LOGGER.error("%s: %s stream %s frame %s error (%s): %s",
                          self.name, node_name, stream.stream_id,
                          frame.frame_id, reason, diagnostic)
            self._dead_letter(stream, frame, node_name, reason,
                              diagnostic)
            self._finish_frame(stream, frame, dropped=True, error=True)
            if budget_tripped:
                self._quarantine_stream(stream)
            return False
        # stop_stream: the original engine contract -- the stream dies,
        # the pipeline survives
        _LOGGER.error("%s: %s stream %s error: %s", self.name,
                      node_name, stream.stream_id, diagnostic)
        self._dead_letter(stream, frame, node_name, "stop_stream",
                          diagnostic)
        self._finish_frame(stream, frame, error=True)
        self.destroy_stream(stream.stream_id, state=StreamState.ERROR)
        return False

    def _retry_element(self, stream_id, frame_id, node_name) -> None:
        """Mailbox/timer continuation of a scheduled retry: un-park the
        node and re-enter the frame's graph pass (the node re-dispatches
        inline or re-parks for micro-batching, exactly like a first
        attempt)."""
        stream = self.streams.get(str(stream_id))
        if stream is None:
            return  # stream destroyed while the backoff ran
        frame = stream.frames.get(int(frame_id))
        if frame is None:
            return  # frame released meanwhile (deadline/watchdog)
        node_name = str(node_name)
        frame.pending_nodes.discard(node_name)
        self._run_frame(stream, frame, resume_after=None)

    def _stream_parameter(self, stream: Stream, name: str, default):
        """Stream-level parameter with pipeline-definition fallback (for
        knobs that are per-stream, not per-element)."""
        if stream.parameters and name in stream.parameters:
            return stream.parameters[name]
        return (self.definition.parameters or {}).get(name, default)

    def _note_stream_error(self, stream: Stream) -> bool:
        """Record one error against the stream's sliding error budget;
        True when the budget tripped (caller quarantines).  Budget off
        (the default) costs one parameter lookup on the ERROR path
        only."""
        budget = parse_int(
            self._stream_parameter(stream, "error_budget", 0), 0)
        if budget <= 0:
            return False
        window = parse_float(
            self._stream_parameter(stream, "error_window",
                                   DEFAULT_ERROR_WINDOW),
            DEFAULT_ERROR_WINDOW) or DEFAULT_ERROR_WINDOW
        times = stream.error_times
        if times is None:
            times = stream.error_times = deque()
        now = time.monotonic()
        times.append(now)
        while times and times[0] < now - window:
            times.popleft()
        return len(times) >= budget

    def _quarantine_stream(self, stream: Stream) -> None:
        _LOGGER.error(
            "%s: stream %s blew its error budget; quarantining",
            self.name, stream.stream_id)
        self.telemetry.record_breaker_trip(stream.stream_id)
        self.destroy_stream(stream.stream_id, state=StreamState.ERROR)

    def _frame_deadline(self, stream: Stream) -> float:
        """The stream's `frame_deadline` seconds (0 = disabled),
        memoized: stream parameters are fixed at create_stream."""
        cached = getattr(stream, "_frame_deadline_s", None)
        if cached is None:
            cached = parse_float(self._stream_parameter(
                stream, "frame_deadline", 0.0), 0.0)
            stream._frame_deadline_s = cached
        return cached

    def _arm_frame_deadline(self, stream: Stream, frame: Frame,
                            deadline_s: float) -> None:
        """Bound the frame's END-TO-END residence time.  Generalizes the
        doubtful-park watchdog: that one only covers parks whose
        attribution came into doubt, while this covers every way a frame
        can stall -- a dead RemoteElement, a lost async reply, a
        wedged element -- and releases the frame (dead-lettered) so its
        backpressure slot returns well before the stream lease expires."""
        stream_id, frame_id = stream.stream_id, frame.frame_id

        def expired(_uuid):
            frame.deadline_lease = None
            live_stream = self.streams.get(stream_id)
            if live_stream is None:
                return
            if live_stream.frames.get(frame_id) is not frame:
                return  # finished in time
            _LOGGER.warning(
                "%s: frame %s/%s exceeded frame_deadline %.2fs "
                "(pending: %s); releasing as error", self.name,
                stream_id, frame_id, deadline_s,
                sorted(frame.pending_nodes) or "none")
            self.telemetry.record_deadline_expired(frame)
            self._dead_letter(
                live_stream, frame, None, "frame_deadline",
                f"frame exceeded {deadline_s}s with "
                f"{sorted(frame.pending_nodes)} in flight")
            self._finish_frame(live_stream, frame, dropped=True,
                               error=True)

        frame.deadline_lease = Lease(
            self.process.event, deadline_s,
            f"deadline:{stream_id}:{frame_id}",
            lease_expired_handler=expired)

    @staticmethod
    def _describe_value(value) -> str:
        """Compact dead-letter descriptor entry: shape/dtype for arrays,
        length for strings -- evidence of WHAT was in flight, never the
        payload itself."""
        if hasattr(value, "shape") and hasattr(value, "dtype"):
            return f"{value.dtype}{list(value.shape)}"
        if isinstance(value, (str, bytes)):
            return f"{type(value).__name__}[{len(value)}]"
        if isinstance(value, (list, tuple)):
            return f"{type(value).__name__}[{len(value)}]"
        return type(value).__name__

    def _dead_letter(self, stream: Stream, frame: Frame,
                     node_name, reason: str, diagnostic) -> None:
        """Publish the failed frame's evidence on
        `{topic_path}/dead_letter`: inputs DESCRIPTOR (swag keys with
        shapes/dtypes), diagnostic, and the frame's trace id so the
        failure joins its trace in the Perfetto export.  Consumed by the
        Recorder; export failures never mask the engine's own
        recovery."""
        self.telemetry.record_dead_letter(node_name, reason)
        trace = frame.trace
        meta = {
            "stream_id": stream.stream_id,
            "frame_id": frame.frame_id,
            "node": str(node_name) if node_name else "",
            "reason": reason,
            "trace_id": trace.trace_id if trace is not None else "",
            "diagnostic":
                str(diagnostic)[:_DEAD_LETTER_DIAGNOSTIC_CAP],
        }
        descriptor = {str(key): self._describe_value(value)
                      for key, value in frame.swag.items()}
        try:
            import os as _os
            cap = int(_os.environ.get("AIKO_DEAD_LETTER_DATA_MAX",
                                      _DEAD_LETTER_DATA_CAP))
            if cap > 0:
                encoded = encode_frame_data(dict(frame.swag))
                if len(encoded) <= cap:
                    meta["data"] = encoded
        except Exception:
            pass  # unencodable swag: descriptor-only dead letter
        try:
            self.process.publish(
                f"{self.topic_path}/dead_letter",
                generate("dead_letter", [meta, descriptor]))
        except Exception as error:
            _LOGGER.warning("%s: dead-letter publish failed: %s",
                            self.name, error)

    def _note_fused_failure(self, node_name: str, outputs) -> None:
        """A fused group program failed at RUN time (resolve-time
        failures already fall back in _resolve_group_kernel).  Count it;
        at FUSED_FLAP_LIMIT CONSECUTIVE failures (a later healthy fused
        group resets the count) the node's fused path is pinned off --
        a flapping kernel must not pay fused-failure + chained-retry on
        every group."""
        count = self._fused_failures.get(node_name, 0) + 1
        self._fused_failures[node_name] = count
        disabled = (count >= FUSED_FLAP_LIMIT
                    and node_name not in self._fused_disabled)
        if disabled:
            self._fused_disabled.add(node_name)
            self._fused_programs.pop(node_name, None)
            _LOGGER.warning(
                "%s: %s fused group path failed %d times; pinned to "
                "the chained path: %s", self.name, node_name, count,
                _diagnostic_of(outputs))
        else:
            _LOGGER.warning(
                "%s: %s fused group failed (%d/%d); retrying the group "
                "on the chained path: %s", self.name, node_name, count,
                FUSED_FLAP_LIMIT, _diagnostic_of(outputs))
        self.telemetry.record_fused_failure(node_name, disabled)

    # -- micro-batching (no reference counterpart: the reference processes
    # one frame per mailbox message, pipeline.py:1037-1092; on TPU the MFU
    # multiplier is coalescing queued frames into ONE jit call) ------------

    @staticmethod
    def _micro_signature(inputs: dict):
        """Frames coalesce only when every input agrees on FULL shape and
        dtype -- including the leading/batch size, so a coalesced group
        is always `k` equal-row stacks and the concat program is
        shape-stable (each distinct eager-op shape costs an XLA compile,
        painful on tunneled devices)."""
        leading = None
        signature = []
        for name in sorted(inputs):
            value = inputs[name]
            if not hasattr(value, "shape") or getattr(value, "ndim", 0) < 1:
                return None  # non-array input: not coalescable
            if leading is None:
                leading = value.shape[0]
            elif value.shape[0] != leading:
                return None  # inputs disagree on the batch axis
            signature.append(
                (name, tuple(value.shape[1:]), str(value.dtype)))
        if leading is None:
            return None
        return (leading, tuple(signature))

    def _micro_param_fingerprint(self, stream: Stream, node_name: str,
                                 definition):
        """Stream-parameter fingerprint gating CROSS-STREAM coalescing:
        frames from different streams may share one jit call only when
        both streams would resolve EVERY parameter identically.
        Conservative by design: the whole stream-parameter dict is
        fingerprinted (not just declared keys), so an element reading
        an undeclared per-stream knob via get_parameter(name, default)
        can never silently share a call resolved under another
        stream's values -- the failure mode is a smaller batch, never
        wrong output.  Values are canonically encoded (sorted keys,
        content-hashed arrays); repr() is not used because it
        truncates large ndarrays, letting different values compare
        equal.  Memoized per stream: stream parameters are fixed at
        create_stream (no mutation path exists), so hashing arrays
        every parked frame would be pure waste."""
        del node_name, definition  # every key participates
        cached = getattr(stream, "_micro_param_fingerprint", None)
        if cached is None:
            cached = _canonical_value(stream.parameters or {})
            stream._micro_param_fingerprint = cached
        return cached

    def _try_park_micro(self, stream: Stream, frame: Frame, node_name: str,
                        element, inputs: dict) -> bool:
        """Park the frame for coalesced execution when the element opts in
        (micro_batch > 1).  The flush message rides the back of the
        pipeline mailbox, so every frame already queued parks first --
        batch size adapts to instantaneous load (deep queue = big batch,
        idle = batch of one, so latency stays flat when unloaded).  The
        pending list is PER ELEMENT, not per stream: the serving
        scenario (many concurrent streams, one frame each) coalesces
        across streams into one jit call, with each frame resuming on
        its own stream.  The mailbox ride is also the starvation bound:
        a parked frame waits at most the messages already queued ahead
        of it, never for more traffic."""
        if isinstance(element, AsyncHostElement):
            return False  # async elements manage their own parking
        if element.engine_managed(stream):
            # the element runs its OWN batching engine (LMGenerate
            # `continuous: true`): frames must reach process_frame
            # one-by-one so the engine can admit them into the running
            # decode loop at prefill boundaries -- holding them in a
            # coalesced group would reintroduce exactly the closed-batch
            # convoy the engine exists to remove
            return False
        try:
            micro = int(element.get_parameter("micro_batch", 1, stream) or 1)
        except (TypeError, ValueError):
            return False
        if micro <= 1:
            return False
        shape_signature = self._micro_signature(inputs)
        if shape_signature is None:
            return False
        signature = (shape_signature, self._micro_param_fingerprint(
            stream, node_name, element.definition))
        pending = self._micro_pending.setdefault(node_name, [])
        frame.pending_nodes.add(node_name)
        pending.append((stream, frame, inputs, signature))
        # opens the queue-wait interval (closed at coalesced dispatch)
        self.telemetry.mark_park(frame, node_name, kind="micro")
        # capacity counts THIS signature only: mixed-signature traffic
        # (stream cohorts with different shapes or parameters) must not
        # trigger a flush that chronically splits every cohort into
        # partial groups -- each cohort fills to its own micro
        same_signature = sum(
            1 for entry in pending if entry[3] == signature)
        if same_signature >= micro:
            self._flush_micro_batch(node_name, signature=signature)
        elif len(pending) == 1:
            # micro_batch_wait_ms > 0: HOLD the flush for a bounded
            # window so trickling arrivals (the serving steady state --
            # each stream replenishes one frame per completion, so the
            # mailbox is usually empty and an immediate flush would run
            # batches of one) can coalesce.  The window is the explicit
            # starvation bound; 0 keeps the pure mailbox ride (batch
            # adapts to queue depth, zero added latency)
            try:
                wait_ms = float(element.get_parameter(
                    "micro_batch_wait_ms", 0, stream) or 0)
            except (TypeError, ValueError):
                wait_ms = 0.0
            if wait_ms > 0:
                self._schedule_micro_flush(node_name, wait_ms / 1000.0)
            else:
                self.post_message("_flush_micro_batch", [node_name])
        return True

    def _schedule_micro_flush(self, node_name: str, wait_s: float) -> None:
        """One-shot timer posting a flush for `node_name` after
        `wait_s` (the continuous-batching hold-down window).  Tracked in
        _micro_timers so a capacity-triggered flush cancels it (an
        orphan timer would fire early into the next batch's window)."""
        if node_name in self._micro_timers:
            return  # a window is already open
        gen = self._micro_flush_gen.get(node_name, 0)

        def fire():
            self.process.event.remove_timer_handler(fire)
            self._micro_timers.pop(node_name, None)
            # the generation rides along: if a capacity flush supersedes
            # this window before the message is processed, it is ignored
            self.post_message("_flush_micro_batch",
                              [node_name, None, gen])

        self._micro_timers[node_name] = fire
        self.process.event.add_timer_handler(fire, wait_s)

    def _flush_micro_batch(self, element_name, _legacy_stream_id=None,
                           gen=None, signature=None):
        node_name = str(element_name)
        if gen is not None and gen != self._micro_flush_gen.get(
                node_name, 0):
            # a hold-down timer's posted message from a window that a
            # capacity flush already superseded: ignoring it keeps it
            # from prematurely flushing the NEXT accumulating batch
            return
        pending = self._micro_pending.pop(node_name, None)
        if signature is not None and pending:
            # capacity flush for ONE ripe signature: other cohorts'
            # partial groups stay parked (their open hold-down window
            # or the mailbox-riding flush message still covers them,
            # so nothing starves)
            rest = [entry for entry in pending if entry[3] != signature]
            pending = [entry for entry in pending
                       if entry[3] == signature]
            if rest:
                self._micro_pending[node_name] = rest
        if node_name not in self._micro_pending:
            # everything consumed: supersede the open window so a
            # stale timer cannot fire early into the NEXT batch
            self._micro_flush_gen[node_name] = (
                self._micro_flush_gen.get(node_name, 0) + 1)
            fire = self._micro_timers.pop(node_name, None)
            if fire is not None:
                self.process.event.remove_timer_handler(fire)
        if not pending:
            return
        element = self.elements.get(node_name)
        if element is None or isinstance(element, RemoteElement):
            return
        if self.telemetry.enabled or (
                node_name not in self._micro_cohort_logged
                and _LOGGER.isEnabledFor(logging.DEBUG)):
            # only scan when someone consumes the result: the counter
            # (telemetry on) or the one-time debug log -- with
            # telemetry disabled and debug off the flush path stays
            # scan-free (the latency operating point's cost contract)
            # same shapes but different parameter fingerprints: streams
            # that cannot share a call.  ONE split event per flush (the
            # widest shape's cohort count), counted so operators watch
            # the rate live; said once (debug) so the log shows WHY
            # coalesced groups came up small instead of it degrading
            # silently
            fingerprints_by_shape: dict = {}
            for entry in pending:
                fingerprints_by_shape.setdefault(
                    entry[3][0], set()).add(entry[3][1])
            cohorts = max((len(prints) for prints
                           in fingerprints_by_shape.values()), default=0)
            if cohorts > 1:
                self.telemetry.record_cohort_split(node_name, cohorts)
                if node_name not in self._micro_cohort_logged:
                    self._micro_cohort_logged.add(node_name)
                    _LOGGER.debug(
                        "%s: %s parked frames split into %d "
                        "parameter-fingerprint cohorts (streams resolve "
                        "parameters differently, so cross-stream "
                        "coalescing runs smaller groups)",
                        self.name, node_name, cohorts)
        # gather-by-signature, FIFO by first occurrence: interleaved
        # streams with matching shapes+parameters coalesce; a
        # mismatched head never blocks later matching entries.  micro
        # capacity resolves per GROUP from its head entry's stream
        # (fingerprint equality makes every member agree, but different
        # fingerprint groups may configure different capacities)
        while pending:
            signature = pending[0][3]
            micro = max(1, int(element.get_parameter(
                "micro_batch", 1, pending[0][0]) or 1))
            group, rest = [], []
            for entry in pending:
                if len(group) < micro and entry[3] == signature:
                    group.append(entry)
                else:
                    rest.append(entry)
            pending = rest
            # frames finished elsewhere / destroyed streams: never resume
            group = [
                entry for entry in group
                if self.streams.get(entry[0].stream_id) is entry[0]
                and entry[0].frames.get(entry[1].frame_id) is entry[1]]
            if group:
                self._run_micro_group(element, group, micro)

    def _run_micro_group(self, element, group: list, micro: int) -> None:
        """One coalesced element call for `group` parked frames
        (possibly from SEVERAL streams): concat inputs on axis 0 --
        padded by default to the FULL micro_batch row count, so
        rampup/drain partial groups reuse the steady-state compilation
        (micro_batch_pad_full=false falls back to power-of-two buckets)
        -- split outputs back per frame, resume each through the normal
        graph path ON ITS OWN STREAM (per-stream response routing).

        Two execution paths: elements exposing a group kernel run
        concat+pad+kernel+split as ONE fused program
        (_call_fused_group); everything else runs the chained
        jitted-concat -> process_frame -> jitted-split trio."""
        node_name = element.definition.name
        lead_stream = group[0][0]
        rows = [next(iter(inputs.values())).shape[0]
                for _, _, inputs, _ in group]
        total = sum(rows)
        full = rows[0] * micro
        if element.get_parameter("micro_batch_pad_full", True,
                                 lead_stream):
            target = (full if total <= full
                      else bucket_length(total, minimum=rows[0]))
        else:
            target = bucket_length(total, minimum=rows[0])
        # pad the ENTRY LIST to exactly `micro` arrays with zero
        # fillers when padding to full: the concat program is then
        # one fixed shape per signature instead of one per group
        # size (each distinct arity would cost an XLA compile --
        # measured to dominate serving throughput on the tunnel).
        # split_rows mirrors the fillers so partial (rampup/drain)
        # groups also reuse the steady-state SPLIT executable
        fillers = (micro - len(group)
                   if target == full and len(group) < micro else 0)
        split_rows = rows + [rows[0]] * fillers if fillers else rows
        kernel_spec = self._resolve_group_kernel(element, lead_stream)
        # the element sees the LEAD stream (parameter fingerprints
        # guarantee every stream in the group resolves its parameters
        # identically, so the choice is immaterial)
        lead_stream.current_frame_id = group[0][1].frame_id
        # coalesced dispatch: close every member's queue-wait interval
        # (park -> here is scheduler-induced latency, reported apart
        # from element/device time) and record the group shape
        for _, parked_frame, _, _ in group:
            self.telemetry.record_queue_wait(parked_frame, node_name)
        self.telemetry.record_group(node_name, len(group), target,
                                    fused=kernel_spec is not None)
        per_frame = None
        element_start = time.perf_counter()
        # injected per-frame faults: a SINGLETON group consumes its
        # fault here (it goes straight to the error policy -- no
        # isolation pass would ever consume it); a multi-frame group
        # only PEEKS, so the consumable fires at the per-frame
        # isolation call and healthy cohort members complete
        if self.faults is None:
            poisoned = False
        elif len(group) == 1:
            poisoned = self.faults.element_raise(
                node_name, group[0][1].frame_id, group[0][0].stream_id)
        else:
            poisoned = any(
                self.faults.element_raise_pending(
                    node_name, parked.frame_id, parked_stream.stream_id)
                for parked_stream, parked, _, _ in group)
        if poisoned:
            stream_event, outputs = StreamEvent.ERROR, {
                "diagnostic": f"{node_name}: injected fault in "
                              f"coalesced group"}
            # NOT a fused flap: the kernel never executed (the injected
            # fault models a poisoned ELEMENT input, not a kernel bug),
            # so the breaker must not pin a healthy kernel chained
            kernel_spec = None
        elif kernel_spec is not None:
            stream_event, outputs, per_frame = self._call_fused_group(
                element, group, kernel_spec, target, split_rows, fillers)
            if stream_event == StreamEvent.ERROR:
                # a failed fused group is NOT lost: count the flap
                # (FUSED_FLAP_LIMIT pins the node chained) and retry the
                # whole group through the chained path before any
                # per-frame isolation
                self._note_fused_failure(node_name, outputs)
                per_frame = None
                kernel_spec = None
                stream_event, outputs = self._call_chained_group(
                    element, group, lead_stream, target, total, fillers)
            elif node_name in self._fused_failures:
                # a healthy fused group closes the flap window: only
                # CONSECUTIVE failures trip the breaker, so scattered
                # poison frames over a long deployment never pin a
                # healthy kernel to the chained path
                self._fused_failures.pop(node_name, None)
        else:
            stream_event, outputs = self._call_chained_group(
                element, group, lead_stream, target, total, fillers)
        elapsed = time.perf_counter() - element_start
        share = elapsed / len(group)
        contract_violation = False
        if stream_event == StreamEvent.PENDING:
            if len(group) == 1:
                # element continues off the event loop and resumes the
                # frame via process_frame_response (frame stays parked
                # in pending_nodes; the fallback-identity slot is only
                # claimed when no remote hop holds it)
                if group[0][1].paused_pe_name is None:
                    group[0][1].paused_pe_name = node_name
                return
            contract_violation = True
            stream_event, outputs = StreamEvent.ERROR, {
                "diagnostic": (
                    f"{node_name}: StreamEvent.PENDING is incompatible "
                    f"with micro_batch > 1 (the async continuation can "
                    f"only resume one frame); use an AsyncHostElement "
                    f"or micro_batch: 1")}
        if stream_event == StreamEvent.OKAY:
            if per_frame is None:  # chained path: split as its own program
                shared_outputs = {
                    port["name"] for port in element.definition.output
                    if not port.get("batched", True)}
                per_frame = self._split_micro_outputs_all(
                    outputs or {}, split_rows, target, shared_outputs)
            for (stream, frame, _, _), frame_outputs in zip(group,
                                                            per_frame):
                if (self.streams.get(stream.stream_id) is not stream
                        or stream.frames.get(frame.frame_id) is not frame):
                    continue  # finished/destroyed meanwhile
                self.telemetry.record_element(
                    frame, node_name, element_start, share,
                    path=("fused" if kernel_spec is not None
                          else "chained"), group=len(group))
                frame.swag.update(self._map_out(frame_outputs,
                                                element.definition))
                frame.pending_nodes.discard(node_name)
                stream.current_frame_id = frame.frame_id
                self._run_frame(stream, frame, resume_after=node_name)
        else:
            # non-OKAY applies to the whole coalesced call: release every
            # frame under the same StreamEvent policy as the inline path,
            # each on its own stream
            for stream, frame, _, _ in group:
                frame.pending_nodes.discard(node_name)
                self.telemetry.record_element(
                    frame, node_name, element_start, share,
                    path=("fused" if kernel_spec is not None
                          else "chained"), group=len(group))
            if stream_event == StreamEvent.DROP_FRAME:
                for stream, frame, _, _ in group:
                    self._finish_frame(stream, frame, dropped=True)
            elif stream_event == StreamEvent.STOP:
                _LOGGER.info("%s: %s requested stream stop: %s",
                             self.name, node_name, outputs)
                for stream, frame, _, _ in group:
                    self._finish_frame(stream, frame)
                for stream_id in dict.fromkeys(
                        stream.stream_id for stream, _, _, _ in group):
                    self.destroy_stream(stream_id, graceful=True)
            elif contract_violation or (
                    len(group) > 1
                    and element.resolve_error_policy(
                        lead_stream).on_error == "stop_stream"):
                # the legacy hard-stop: a misdeclared element (PENDING
                # from a coalesced call) OR a group under the default
                # stop_stream policy -- the parameter fingerprint makes
                # the policy uniform across the group, and re-executing
                # members in isolation would both duplicate side
                # effects and break the historical contract the default
                # preserves
                _LOGGER.error("%s: %s error: %s", self.name, node_name,
                              _diagnostic_of(outputs))
                for stream, frame, _, _ in group:
                    self._dead_letter(stream, frame, node_name,
                                      "stop_stream",
                                      _diagnostic_of(outputs))
                    self._finish_frame(stream, frame, error=True)
                for stream_id in dict.fromkeys(
                        stream.stream_id for stream, _, _, _ in group):
                    self.destroy_stream(stream_id,
                                        state=StreamState.ERROR)
            elif len(group) == 1:
                stream, frame, _, _ = group[0]
                if (self.streams.get(stream.stream_id) is stream
                        and stream.frames.get(frame.frame_id) is frame):
                    self._handle_element_error(stream, frame, node_name,
                                               element, outputs)
            else:
                # both whole-group attempts failed under an opted-in
                # recovery policy (drop_frame/retry): one poison frame
                # must not kill its cohort -- split to per-frame
                # isolation, where each member takes its own
                # error-policy path.  Opting in accepts at-least-once
                # element execution for the group's members
                self._isolate_micro_group(element, group, node_name,
                                          outputs)

    def _call_chained_group(self, element, group: list,
                            lead_stream: Stream, target: int, total: int,
                            fillers: int) -> tuple:
        """The chained micro-batch call: jitted concat+pad, then ONE
        process_frame over the coalesced batch (also the retry path for
        a failed fused group)."""
        node_name = element.definition.name
        if len(group) == 1 and target == total:
            coalesced = dict(group[0][2])
        else:
            named_arrays = self._gather_named_arrays(group, fillers)
            coalesced = _concat_pad_program(named_arrays, target)
        return self._safe_call(node_name, element.process_frame,
                               lead_stream, **coalesced)

    def _isolate_micro_group(self, element, group: list, node_name: str,
                             group_outputs) -> None:
        """Per-frame isolation after a whole-group failure: run each
        member individually with ITS OWN inputs so healthy frames
        complete and only the poison frame takes the element's error
        policy (retry re-parks it through the scheduler; drop_frame
        dead-letters it; stop_stream kills only its own stream)."""
        _LOGGER.warning(
            "%s: %s coalesced group of %d failed (%s); splitting to "
            "per-frame isolation", self.name, node_name, len(group),
            _diagnostic_of(group_outputs))
        for stream, frame, inputs, _ in group:
            if (self.streams.get(stream.stream_id) is not stream
                    or stream.frames.get(frame.frame_id) is not frame):
                continue  # finished/destroyed meanwhile
            stream.current_frame_id = frame.frame_id
            stream_event, outputs = self._dispatch_element(
                stream, frame, node_name, element, inputs)
            if stream_event == StreamEvent.OKAY:
                frame.swag.update(self._map_out(outputs or {},
                                                element.definition))
                self._run_frame(stream, frame, resume_after=node_name)
            elif stream_event == StreamEvent.PENDING:
                # the isolated call parked this frame alone -- the
                # single-frame PENDING contract applies
                frame.pending_nodes.add(node_name)
                if frame.paused_pe_name is None:
                    frame.paused_pe_name = node_name
            elif stream_event == StreamEvent.DROP_FRAME:
                self._finish_frame(stream, frame, dropped=True)
            elif stream_event == StreamEvent.STOP:
                self._finish_frame(stream, frame)
                self.destroy_stream(stream.stream_id, graceful=True)
            else:
                self._handle_element_error(stream, frame, node_name,
                                           element, outputs)

    def _gather_named_arrays(self, group: list, fillers: int) -> dict:
        """{input name: tuple of per-frame arrays}, entry list padded
        with cached zero fillers to keep arity stable (one compile per
        signature, not per group size)."""
        import jax.numpy as jnp
        named_arrays = {}
        for name in group[0][2]:
            arrays = [inputs[name] for _, _, inputs, _ in group]
            if fillers:
                key = (tuple(arrays[0].shape), str(arrays[0].dtype))
                filler = self._micro_fillers.get(key)
                if filler is None:
                    if len(self._micro_fillers) >= 32:
                        # bounded: variable-shape workloads must not
                        # pin device buffers forever
                        self._micro_fillers.clear()
                    filler = jnp.zeros_like(arrays[0])
                    self._micro_fillers[key] = filler
                arrays.extend([filler] * fillers)
            named_arrays[name] = tuple(arrays)
        return named_arrays

    def _resolve_group_kernel(self, element, stream: Stream):
        """The element's fused-path hook, resolved defensively: an
        unimplemented hook, a falsy `micro_batch_fused` parameter, or a
        raising hook all fall back to the chained path (the failure
        mode is the pre-fusion dispatch chain, never a lost frame)."""
        if (type(element).group_kernel
                is PipelineElement.group_kernel):
            return None  # hook not implemented: chained path
        if element.definition.name in self._fused_disabled:
            return None  # circuit breaker: flapping kernel pinned chained
        from ..utils import truthy
        if not truthy(element.get_parameter(
                "micro_batch_fused", True, stream)):
            return None
        try:
            spec = element.group_kernel(stream)
            if spec is None:
                return None
            kernel, context = spec  # malformed return -> chained path
            if not callable(kernel):
                raise TypeError(
                    f"group_kernel must return (callable, context), "
                    f"got ({type(kernel).__name__}, ...)")
        except Exception as error:
            if element.definition.name not in self._fused_rejected:
                self._fused_rejected.add(element.definition.name)
                _LOGGER.warning(
                    "%s: %s group_kernel failed (%s); using the chained "
                    "micro-batch path", self.name,
                    element.definition.name, error)
            return None
        return kernel, context

    def _call_fused_group(self, element, group: list, kernel_spec,
                          target: int, split_rows: list,
                          fillers: int) -> tuple:
        """ONE compiled XLA program for the whole group: the concat+pad
        of every input, the element's group kernel, and the per-frame
        output split trace together, so the tunneled dispatch cost is
        paid once per group instead of three times (standalone probe,
        round 5: 1 642 frames/s fused vs 1 403 chained vs 310 eager on
        the yolov8n serving chain).  Returns (StreamEvent, outputs,
        per-frame output dicts | None)."""
        kernel, context = kernel_spec
        named_arrays = self._gather_named_arrays(group, fillers)
        shared = tuple(sorted(
            port["name"] for port in element.definition.output
            if not port.get("batched", True)))
        program = self._fused_program_for(element.definition.name, kernel)
        try:
            per_frame = program(
                context, named_arrays, target=int(target),
                counts=tuple(int(count) for count in split_rows),
                shared=shared)
        except Exception as error:
            return StreamEvent.ERROR, {
                "diagnostic": f"{element.definition.name}: fused group "
                              f"kernel failed: {error}",
                "traceback": traceback.format_exc()}, None
        return StreamEvent.OKAY, {}, list(per_frame[:len(group)])

    def _fused_program_for(self, node_name: str, kernel):
        """Cached jit of concat+pad -> kernel -> split for one element,
        keyed by kernel identity: elements keep their kernel objects
        stable (one per static parameter value), so each program (and
        every per-signature executable under it) persists across groups
        even when cohorts alternate; a fresh kernel closure only costs
        a rebuild, never a wrong result.  The id key stays valid while
        the entry holds the kernel strongly; a reused id after GC fails
        the identity check and rebuilds."""
        programs = self._fused_programs.setdefault(node_name, {})
        entry = programs.get(id(kernel))
        if entry is not None and entry[0] is kernel:
            return entry[1]
        import functools

        import jax

        def slice_rows(value, offset, count, target):
            if isinstance(value, dict):
                return {name: slice_rows(child, offset, count, target)
                        for name, child in value.items()}
            if (hasattr(value, "ndim") and getattr(value, "ndim", 0) >= 1
                    and value.shape[0] == target):
                return value[offset:offset + count]
            if isinstance(value, list) and len(value) == target:
                # per-row Python list: same split rule as the chained
                # path's _split_micro_outputs_all host-list branch
                return value[offset:offset + count]
            return value  # leading axis not the batch: shared whole

        @functools.partial(jax.jit,
                           static_argnames=("target", "counts", "shared"))
        def fused(context, named, target, counts, shared):
            batch = _concat_pad(named, target)
            outputs = kernel(context, **batch)
            if not isinstance(outputs, dict):
                raise TypeError(
                    f"{node_name}: group kernel must return a dict, "
                    f"got {type(outputs)}")
            frames = []
            offset = 0
            for count in counts:
                frames.append({
                    name: (value if name in shared
                           else slice_rows(value, offset, count, target))
                    for name, value in outputs.items()})
                offset += count
            return tuple(frames)

        if len(programs) >= 8:
            # bounded: an element returning a FRESH closure every call
            # must not leak one dead program per group
            programs.clear()
        programs[id(kernel)] = (kernel, fused)
        # a fresh fused program means a fresh XLA compile per signature
        # underneath: counted + traced so compile storms are attributable
        self.telemetry.record_compile(node_name, "fused")
        return fused

    def _split_micro_outputs_all(self, outputs: dict, rows: list,
                                 target: int, shared: set) -> list:
        """Per-frame output dicts for a whole coalesced group, with ALL
        device slicing folded into ONE jitted program.

        Split semantics: arrays (and lists) whose leading size matches
        the coalesced batch split by row range, recursing into nested
        dicts (e.g. the Detector's {"detections": {boxes, scores, ...}}
        contract); anything else -- and outputs named in `shared` (ports
        declared "batched": false) -- is shared by every frame.

        Why batched: a per-frame eager slice costs a device dispatch
        EACH (4 leaves x 16 frames = 64 launches per group, which
        dominated serving throughput on the tunnel); here every frame's
        slice of every device leaf is one fixed-shape program, cached
        across groups."""
        import jax
        device_leaves = []

        def plan(value, top_name=None):
            if top_name is not None and top_name in shared:
                return ("whole", value)
            if isinstance(value, dict):
                return ("dict", {name: plan(child)
                                 for name, child in value.items()})
            if (isinstance(value, jax.Array)
                    and getattr(value, "ndim", 0) >= 1
                    and value.shape[0] == target):
                device_leaves.append(value)
                return ("device", len(device_leaves) - 1)
            if (hasattr(value, "shape")
                    and getattr(value, "ndim", 0) >= 1
                    and value.shape[0] == target):
                return ("host", value)   # numpy: slicing is a free view
            if isinstance(value, list) and len(value) == target:
                return ("host", value)
            return ("whole", value)

        skeleton = {name: plan(value, name)
                    for name, value in (outputs or {}).items()}
        counts = tuple(int(count) for count in rows)
        parts = (_split_leaves_program(tuple(device_leaves), counts)
                 if device_leaves else None)
        offsets = []
        offset = 0
        for count in counts:
            offsets.append(offset)
            offset += count

        def build(node, index):
            kind, payload = node
            if kind == "dict":
                return {name: build(child, index)
                        for name, child in payload.items()}
            if kind == "device":
                return parts[index][payload]
            if kind == "host":
                start = offsets[index]
                return payload[start:start + counts[index]]
            return payload  # whole: shared by every frame

        return [
            {name: build(node, index) for name, node in skeleton.items()}
            for index in range(len(counts))]

    def _arm_park_watchdog(self, stream: Stream, frame: Frame,
                           doubtful) -> None:
        """One-shot timer releasing a frame whose park attribution is in
        doubt: if the DOUBTFUL parks (snapshot at arming) resume normally
        the watchdog is a no-op -- later parks on other nodes are healthy
        and must not be killed; if a doubtful park never resumes
        (misbehaving PENDING element), the frame is released as an error
        instead of leaking until the stream dies."""
        frame.park_doubtful |= set(doubtful)
        if frame.park_watchdog is not None:
            # a later unroutable response over DIFFERENT parks: the
            # union above keeps them covered; restart the clock
            frame.park_watchdog.extend()
            return
        try:
            timeout = float(stream.parameters.get("park_timeout", 10.0))
        except (TypeError, ValueError):
            timeout = 10.0
        stream_id, frame_id = stream.stream_id, frame.frame_id

        def expired(_uuid):
            frame.park_watchdog = None  # always allow a later re-arm
            live_stream = self.streams.get(stream_id)
            if live_stream is None:
                return
            live_frame = live_stream.frames.get(frame_id)
            if live_frame is not frame:
                return  # finished meanwhile
            still_doubtful = frame.pending_nodes & frame.park_doubtful
            if not still_doubtful:
                frame.park_doubtful.clear()
                return  # ambiguity resolved; any current parks are healthy
            _LOGGER.warning(
                "%s: frame %s/%s parks %s still unresolved %.1fs after an "
                "unroutable response; releasing as error", self.name,
                stream_id, frame_id, sorted(still_doubtful), timeout)
            # watchdog kills must show up in telemetry and the dashboard
            # metrics page, not only in this log line
            self.telemetry.record_park_expired(frame, still_doubtful)
            self._dead_letter(
                live_stream, frame, None, "park_expired",
                f"parks {sorted(still_doubtful)} unresolved "
                f"{timeout}s after an unroutable response")
            self._finish_frame(live_stream, frame, dropped=True,
                               error=True)

        frame.park_watchdog = Lease(
            self.process.event, timeout,
            f"park:{stream_id}:{frame_id}", lease_expired_handler=expired)

    def _safe_call(self, node, method, *args, **kwargs) -> tuple:
        """Run one element hook, mapping exceptions and malformed
        returns to StreamEvent.ERROR.  `node` is the graph-node name:
        the diagnostic carries WHICH element blew up, so dead letters
        and logs are attributable without reconstructing the call site
        from a traceback."""
        try:
            result = method(*args, **kwargs)
            if result is None:
                return StreamEvent.OKAY, {}
            if (isinstance(result, tuple) and len(result) == 2
                    and isinstance(result[0], StreamEvent)):
                return result
            return StreamEvent.ERROR, {
                "node": str(node),
                "diagnostic": f"{node}: {method.__qualname__} must "
                              f"return (StreamEvent, dict), got "
                              f"{type(result)}"}
        except Exception as error:
            return StreamEvent.ERROR, {
                "node": str(node),
                "diagnostic": f"{node}: {error}",
                "traceback": traceback.format_exc()}

    def _finish_frame(self, stream: Stream, frame: Frame,
                      dropped: bool = False, error: bool = False) -> None:
        if stream.frames.get(frame.frame_id) is not frame:
            return  # already finished (reentrant resume/flush paths)
        if frame.park_watchdog is not None:
            frame.park_watchdog.terminate()
            frame.park_watchdog = None
        if frame.deadline_lease is not None:
            frame.deadline_lease.terminate()
            frame.deadline_lease = None
        # in-flight branch work for this frame must never resume it:
        # strip it from every micro-batch pending list
        if frame.pending_nodes:
            for node_name, entries in list(self._micro_pending.items()):
                kept = [entry for entry in entries
                        if entry[1] is not frame]
                if len(kept) != len(entries):
                    if kept:
                        self._micro_pending[node_name] = kept
                    else:
                        self._micro_pending.pop(node_name, None)
        stream.frames.pop(frame.frame_id, None)
        if stream.pending > 0:
            stream.pending -= 1
        self._frame_count += 1
        self.telemetry.frame_end(stream, frame, dropped=dropped,
                                 error=error)
        if stream.stop_requested and stream.pending == 0:
            self.destroy_stream(stream.stream_id)
        if not dropped and not error:
            self._respond(stream, frame)
        elif stream.topic_response:
            # A remote caller has this frame parked: notify it the frame was
            # dropped/errored so it releases the frame instead of leaking it
            self.process.publish(
                stream.topic_response,
                generate("process_frame_response", [
                    {"stream_id": stream.stream_id,
                     "frame_id": frame.frame_id,
                     "event": "error" if error else "drop_frame"},
                ]))

    def _respond(self, stream: Stream, frame: Frame) -> None:
        outputs = {key: value for key, value in frame.swag.items()
                   if key in self._produced_keys}
        if stream.queue_response is not None:
            stream.queue_response.put((stream, frame, outputs))
        elif stream.topic_response:
            self.process.publish(
                stream.topic_response,
                generate("process_frame_response", [
                    {"stream_id": stream.stream_id,
                     "frame_id": frame.frame_id},
                    encode_frame_data(outputs).encode("ascii"),
                ]))

    # -- name mapping (reference pipeline.py:1184-1212) --------------------

    def _map_in(self, swag: dict, definition) -> dict:
        inputs = {}
        for port in definition.input:
            swag_key = definition.map_in.get(port["name"], port["name"])
            if swag_key not in swag:
                if port.get("optional"):
                    inputs[port["name"]] = None
                    continue
                raise KeyError(swag_key)
            inputs[port["name"]] = swag[swag_key]
        return inputs

    def _map_out(self, outputs: dict, definition) -> dict:
        mapped = {}
        for port in definition.output:
            name = port["name"]
            if name in outputs:
                mapped[definition.map_out.get(name, name)] = outputs[name]
        return mapped

    # -- live parameters & observability -----------------------------------

    def set_parameter(self, name, value) -> None:
        if self.ec_producer is not None:
            self.ec_producer.update(name, value)
        else:
            self.share[name] = value

    def set_element_parameter(self, element_name, name, value) -> None:
        element = self.elements.get(str(element_name))
        if element is not None and not isinstance(element, RemoteElement):
            element.set_parameter(name, value)

    def load(self) -> dict:
        """Instantaneous load summary: `inflight` frames admitted but
        not finished (across streams), `queue_depth` frames parked in
        the micro-batch scheduler awaiting a coalesced flush, and the
        live stream count.  Cheap enough to read per routed frame: the
        serving gateway's replica selection (power-of-two-choices) and
        admission caps consume exactly this dict -- locally for
        in-process replicas, via the EC share (below, plus the periodic
        telemetry summary) for remote ones."""
        # gateways read this CROSS-THREAD per routing decision while
        # this pipeline's own loop churns streams: snapshot the dicts
        # atomically (list() never yields the GIL) before iterating --
        # a generator over the live dict raised "dictionary changed
        # size during iteration" under a 1,000-stream creation storm,
        # silently losing the create that was being routed
        streams = list(self.streams.values())
        pending = list(self._micro_pending.values())
        return {
            "inflight": sum(stream.pending for stream in streams),
            "queue_depth": sum(len(entries) for entries in pending),
            "streams": len(streams),
        }

    def publish_trace(self, topic_response) -> None:
        """Wire query (`aiko trace collect`): publish this pipeline's
        self-describing Perfetto document -- the live-fleet harvest
        path, mirroring the Recorder's paged dead-letter query.  The
        reply shape lives in observe/collector.py (shared with the
        gateway)."""
        from ..observe import publish_trace_document
        publish_trace_document(self.process, self.telemetry,
                               self.topic_path, topic_response)

    def throttle(self, stream_id, rate) -> None:
        """Wire-invocable backpressure: cap `stream_id`'s frame
        generators at `rate` frames/sec (rate <= 0 lifts the cap).
        Sent by the serving gateway as `(throttle stream rate)` when
        every replica saturates -- slowing the source beats shedding
        its frames."""
        stream = self.streams.get(str(stream_id))
        if stream is None:
            return
        rate = parse_float(rate, 0.0)
        for node_name in self.graph.get_path(stream.graph_path):
            element = self.elements[node_name]
            if isinstance(element, RemoteElement):
                element.call("throttle", [stream.stream_id, rate])
            else:
                element.throttle_frame_generation(stream.stream_id, rate)

    def _update_stream_share(self) -> None:
        if self.ec_producer is not None:
            # staged: stream/frame churn folds into one delta payload
            # per drained mailbox burst instead of two publishes per
            # lease per frame (see ECProducer.stage)
            self.ec_producer.stage("stream_count", len(self.streams))
            self.ec_producer.stage("frame_count", self._frame_count)
            # refresh the load gauge consumed by serving gateways --
            # but load() is O(streams + parked), so a creation BURST
            # (thousands of streams, the lease-jitter scenario) must
            # not go quadratic on the event loop: rate-limit to one
            # refresh per 200 ms; the periodic telemetry heartbeat
            # keeps it fresh between churn events anyway
            now = time.monotonic()
            if now - getattr(self, "_load_shared_at", 0.0) >= 0.2:
                self._load_shared_at = now
                load = self.load()
                self.ec_producer.stage("inflight", load["inflight"])
                self.ec_producer.stage("queue_depth",
                                       load["queue_depth"])

    # -- checkpoint / resume (no reference counterpart: SURVEY.md section 5
    # "Checkpoint/resume: absent"; required for preemptible TPU recovery) --

    def checkpoint(self, checkpointer, step: int):
        """Persist every ComputeElement's device state plus per-stream
        frame cursors."""
        from .tpu_element import ComputeElement
        states = {
            name: element.state
            for name, element in self.elements.items()
            if isinstance(element, ComputeElement)
            and element.state is not None}
        def json_safe(parameters):
            # metadata is a JSON sidecar: keep only values that survive
            # json round-trip (device arrays / bytes are dropped, not
            # stringified -- a missing parameter beats a corrupt one)
            safe = {}
            for name, value in (parameters or {}).items():
                try:
                    json.dumps(value)
                except (TypeError, ValueError):
                    continue
                safe[name] = value
            return safe

        cursors = {
            stream_id: {"frame_id": stream.frame_id,
                        "parameters": json_safe(stream.parameters),
                        "graph_path": stream.graph_path}
            for stream_id, stream in list(self.streams.items())}
        return checkpointer.save(
            step, states,
            metadata={"pipeline": self.definition.name,
                      "streams": cursors})

    def restore_checkpoint(self, checkpointer, step: int | None = None):
        """Restore element states; returns the metadata dict (callers
        recreate streams from metadata["streams"] cursors)."""
        from .tpu_element import ComputeElement
        states, metadata = checkpointer.restore(step)
        if states:
            for name, state in states.items():
                element = self.elements.get(name)
                if isinstance(element, ComputeElement):
                    element.restore_state(state)
        for stream_id, cursor in (metadata.get("streams") or {}).items():
            frame_id = int(cursor.get("frame_id", 0))
            stream = self.streams.get(stream_id)
            if stream is None:
                self.create_stream(stream_id,
                                   parameters=cursor.get("parameters"),
                                   graph_path=cursor.get("graph_path"),
                                   first_frame_id=frame_id)
            elif stream.frame_id < frame_id:
                stream.frame_id = frame_id
        return metadata

    # -- live weight hand-off (elastic-fleet warm start) -------------------
    #
    # A freshly spawned replica re-running setup() re-initializes (or
    # re-loads) every parameter the fleet already holds in HBM.  The
    # transfer plane (pipeline/transfer.py) already moves bulk tensors
    # process-to-process with the broker carrying only descriptors, so a
    # live sibling can STREAM its params instead: export_weights()
    # offers every ComputeElement state leaf and returns a
    # JSON-serializable descriptor tree; the new replica's
    # import_weights() fetches the leaves and installs them through the
    # checkpoint-restore path (restore_state), so mesh placement and
    # the no-double-allocation guarantee are the proven ones.

    def export_weights(self) -> dict:
        """Offer every ComputeElement's device state over the transfer
        plane; returns {element_name: descriptor_tree} where each leaf
        is a `{TENSOR_REF_KEY: descriptor}` marker.  Only elements
        whose state ALREADY exists are exported: this runs on the
        spawner's thread, and forcing a lazy setup() here would race
        the sibling's own event loop mid-frame -- an element that has
        never served simply comes up cold on the importer."""
        import numpy as np
        from .tpu_element import ComputeElement
        from .transfer import TENSOR_REF_KEY, get_transfer_server
        from ..observe.metrics import get_registry
        import jax

        server = get_transfer_server()
        metrics = get_registry()
        exported = {}
        for name, element in self.elements.items():
            if not isinstance(element, ComputeElement):
                continue
            if element.state is None:
                continue

            def offer(leaf):
                array = np.asarray(leaf)
                metrics.counter("warm_start.exported_bytes").inc(
                    array.nbytes)
                return {TENSOR_REF_KEY: server.offer(array)}

            exported[name] = jax.tree_util.tree_map(offer, element.state)
        metrics.counter("warm_start.exports").inc()
        return exported

    def import_weights(self, exported: dict) -> list:
        """Fetch a sibling's export_weights() tree and install it:
        returns the element names that received state.  Elements absent
        from the tree (or unknown here) fall back to their own setup()
        untouched -- a partial hand-off is better than none."""
        from .tpu_element import ComputeElement
        from .transfer import TENSOR_REF_KEY, fetch_many
        from ..observe.metrics import get_registry

        metrics = get_registry()

        # two passes: collect every descriptor leaf first, then fetch
        # the whole tree through fetch_many -- ONE connection per
        # producing peer instead of one TCP handshake per leaf (the
        # hand-off of a transformer's parameter tree is dozens of
        # leaves from the same sibling)
        pending: list = []

        def collect(node):
            if isinstance(node, dict):
                if TENSOR_REF_KEY in node:
                    pending.append(node[TENSOR_REF_KEY])
                    return
                for value in node.values():
                    collect(value)
                return
            if isinstance(node, (list, tuple)):
                for value in node:
                    collect(value)
                return
            if node is None:
                return
            # leaves were all replaced by descriptor markers at export:
            # anything else is a container this walk cannot rebuild
            raise ValueError(
                f"import_weights: unsupported state container "
                f"{type(node).__name__} (dict/list/tuple pytrees only)")

        def materialize(node, fetched):
            if isinstance(node, dict):
                if TENSOR_REF_KEY in node:
                    array = next(fetched)
                    metrics.counter("warm_start.imported_bytes").inc(
                        array.nbytes)
                    return array
                return {key: materialize(value, fetched)
                        for key, value in node.items()}
            if isinstance(node, tuple) and hasattr(node, "_fields"):
                # namedtuple pytree node (optimizer states etc.):
                # the constructor takes fields positionally
                return type(node)(*(materialize(value, fetched)
                                    for value in node))
            if isinstance(node, (list, tuple)):
                return type(node)(materialize(value, fetched)
                                  for value in node)
            return None

        installed = []
        start = time.perf_counter()
        for name, tree in (exported or {}).items():
            element = self.elements.get(name)
            if not isinstance(element, ComputeElement):
                _LOGGER.warning("%s: import_weights has no local "
                                "ComputeElement %r; skipped",
                                self.name, name)
                continue
            pending = []
            collect(tree)
            fetched = iter(fetch_many(pending))
            element.restore_state(materialize(tree, fetched))
            installed.append(name)
        metrics.histogram("warm_start.import_s").record(
            time.perf_counter() - start)
        return installed

    def stop(self) -> None:
        self.telemetry.stop()  # final snapshot publish + timer teardown
        for stream_id in list(self.streams):
            self.destroy_stream(stream_id)
        if self._services_cache is not None:
            # the cache is process-shared: detach OUR handlers so a
            # stopped pipeline stops reacting to service churn
            for handler in self._remote_handlers:
                self._services_cache.remove_handler(handler)
            self._remote_handlers.clear()
        for element in self.elements.values():
            if not isinstance(element, RemoteElement):
                element.stop()
        super().stop()


def create_pipeline(process, definition_source, name: str = None) -> Pipeline:
    definition = (definition_source
                  if isinstance(definition_source, PipelineDefinition)
                  else parse_pipeline_definition(definition_source))
    return Pipeline(process, definition, name=name)
