# PipelineElement: one node of a pipeline graph.
#
# Capability parity with the reference element layer (reference:
# src/aiko_services/main/pipeline.py:288-456): elements are Actors (remotely
# discoverable/controllable), implement start_stream / process_frame /
# stop_stream returning (StreamEvent, ...), can inject frames via
# create_frame or a threaded frame generator (create_frames, reference
# pipeline.py:365-416), and resolve parameters with stream > element >
# pipeline precedence (reference pipeline.py:422-456).
#
# The TPU compute contract lives in ComputeElement (tpu_element.py): element
# math is a pure JAX function jitted once and fed jax.Array swag values.

from __future__ import annotations

import threading
import time

from ..runtime import Actor
from ..utils import get_logger, parse_float, parse_int
from .stream import Stream, StreamEvent, StreamState

__all__ = ["ErrorPolicy", "PipelineElement", "AsyncHostElement",
           "FrameGeneratorHandle"]

_LOGGER = get_logger("element")

# `on_error` values an element / stream / pipeline may declare.  The
# default preserves the original engine contract: an element error
# destroys the stream (the pipeline survives).
ERROR_POLICIES = ("stop_stream", "drop_frame", "retry")
DEFAULT_MAX_RETRIES = 2
DEFAULT_RETRY_BACKOFF_MS = 10.0


class ErrorPolicy:
    """Resolved per-element error policy: what the engine does when one
    element call fails for one frame.  Resolved through the normal
    parameter precedence (stream > element > pipeline), so operators set
    a pipeline-wide `on_error` and override per element or per stream."""

    __slots__ = ("on_error", "max_retries", "backoff_s")

    def __init__(self, on_error: str, max_retries: int, backoff_s: float):
        self.on_error = on_error
        self.max_retries = max_retries
        self.backoff_s = backoff_s

    def retry_delay(self, attempt: int) -> float:
        """Exponential backoff: base * 2^(attempt-1) for attempt >= 1."""
        return self.backoff_s * (2.0 ** max(attempt - 1, 0))


class FrameGeneratorHandle:
    """Owns one frame-generator thread for (element, stream)."""

    def __init__(self, element, stream: Stream, frame_generator, rate=None,
                 frame_window: int = 16):
        self.element = element
        self.stream = stream
        self.frame_generator = frame_generator
        self.rate = rate
        self.frame_window = frame_window
        self._terminated = False
        # downstream backpressure (serving gateway `(throttle ...)`
        # control message): a positive override CAPS the generation rate
        # below the configured one; 0/None lifts the cap.  Read each
        # tick so a throttle lands mid-stream without a restart.
        self._rate_cap: float | None = None
        self._thread = threading.Thread(
            target=self._run,
            name=f"frames-{element.name}-{stream.stream_id}", daemon=True)

    def start(self):
        self._thread.start()

    def terminate(self):
        self._terminated = True

    def set_rate(self, rate) -> None:
        """Cap the generation rate (frames/sec); rate <= 0 lifts the
        cap back to the configured rate.  Thread-safe: the generator
        loop re-reads the effective interval every tick."""
        try:
            rate = float(rate)
        except (TypeError, ValueError):
            return
        self._rate_cap = rate if rate > 0 else None

    def _interval(self) -> float:
        rate = self.rate
        cap = self._rate_cap
        if cap is not None and (not rate or cap < rate):
            rate = cap
        return 1.0 / rate if rate else 0.0

    def _run(self):
        pipeline = self.element.pipeline
        stream = self.stream
        interval = self._interval()
        next_time = time.monotonic()
        while not self._terminated and stream.state == StreamState.RUN:
            # backpressure: bound in-flight frames so a fast generator
            # cannot grow the pipeline mailbox without limit
            if stream.pending >= self.frame_window:
                time.sleep(0.0005)
                continue
            effective = self._interval()
            if effective != interval:
                # a throttle landed (or lifted): clamp the schedule to
                # now so a long idle gap is not "owed" as a burst
                interval = effective
                next_time = time.monotonic()
            try:
                stream_event, frame_data = self.frame_generator(
                    stream, stream.frame_id)
            except Exception as error:
                _LOGGER.error("%s: frame generator failed: %s",
                              self.element.name, error)
                stream_event, frame_data = StreamEvent.ERROR, {
                    "diagnostic": str(error)}
            if stream_event == StreamEvent.OKAY:
                pipeline.create_frame(stream, frame_data or {})
            elif stream_event == StreamEvent.STOP:
                # post through the mailbox so the destroy is ordered AFTER
                # already-posted frames, then drains gracefully
                pipeline.post_message(
                    "destroy_stream", [stream.stream_id, "stop", True])
                return
            elif stream_event == StreamEvent.ERROR:
                _LOGGER.error("%s: frame generator error: %s",
                              self.element.name, frame_data)
                # the source's own error policy decides whether a bad
                # tick kills the stream (the historical default) or is
                # skipped like a dropped frame (transient ingest faults
                # -- a camera hiccup -- must not destroy a long-lived
                # serving stream when the operator opts into drop_frame)
                policy = self.element.resolve_error_policy(stream)
                if policy.on_error == "stop_stream":
                    pipeline.post_message(
                        "destroy_stream", [stream.stream_id, "error", True])
                    return
                # drop_frame / retry: skip this tick, keep generating --
                # with a backoff floor so a PERSISTENTLY failing
                # rate-less source (unplugged camera) degrades to a slow
                # error log, not a busy-spinning hot thread
                if not interval:
                    time.sleep(max(policy.backoff_s, 0.001))
            # DROP_FRAME: skip this tick
            if interval:
                next_time += interval
                delay = next_time - time.monotonic()
                if delay > 0:
                    time.sleep(delay)


class PipelineElement(Actor):
    def __init__(self, process, pipeline, definition):
        self.pipeline = pipeline
        self.definition = definition
        name = f"{pipeline.name}.{definition.name}" if pipeline else (
            definition.name)
        super().__init__(process, name)
        self.share.update(dict(definition.parameters))
        self._generators: dict[str, FrameGeneratorHandle] = {}

    # -- the element contract (override these) -----------------------------

    def start_stream(self, stream: Stream, stream_id) -> tuple:
        return StreamEvent.OKAY, None

    def process_frame(self, stream: Stream, **inputs) -> tuple:
        raise NotImplementedError

    def stop_stream(self, stream: Stream, stream_id) -> tuple:
        return StreamEvent.OKAY, None

    def group_kernel(self, stream: Stream):
        """Optional fused whole-group execution hook for the micro-batch
        scheduler.  Return `(kernel, context)` where
        `kernel(context, **batch) -> dict` is a PURE jit-traceable
        function (batch-in/batch-out on axis 0, no host side effects)
        and `context` is a pytree of traced values (model state, dynamic
        parameters).  When present, the scheduler traces
        concat+pad+kernel+split as ONE compiled program per (input
        names, arity, shapes) signature instead of three dispatches --
        on tunneled devices each dispatch costs ~10-40 ms, so the fused
        program is the serving hot path.  Contract details:

        - `context` rides the program as a traced argument, never a
          baked-in constant: checkpoint restores and live parameter
          updates apply without a stale executable (return fresh
          context each call; keep the KERNEL's identity stable -- the
          scheduler caches the compiled program per kernel object).
        - Outputs whose leading axis equals the coalesced batch are
          split per frame (recursing into dicts); anything else -- and
          ports declared "batched": false -- is shared whole.
        - Return None (the default) to use the chained
          concat -> process_frame -> split path.
        """
        return None

    def engine_managed(self, stream: Stream) -> bool:
        """True when the element runs its OWN batching engine for this
        stream (e.g. LMGenerate's `continuous: true` slot-based decode
        engine): the micro-batch scheduler must hand it frames
        one-by-one -- the engine admits them into a running device
        loop at prefill boundaries, which strictly dominates
        coalescing whole frames.  Default False (scheduler-managed)."""
        return False

    def eval_kernel(self):
        """Optional abstract-interpretation hook for the static
        analyzer (analyze/shape_eval.py): return `(kernel, state_fn)`
        where `kernel(state, **inputs) -> dict` is the element's pure
        device program and `state_fn()` builds its state pytree (None
        for stateless elements).  Both are ONLY ever called under
        jax.eval_shape, so nothing allocates, compiles, or touches a
        device -- the analyzer synthesizes ShapeDtypeStructs from the
        declared port specs and proves declared outputs match traced
        outputs.  Return None (the default) when the element has no
        pure device program (sources, host elements)."""
        return None

    # -- frame creation ----------------------------------------------------

    def create_frame(self, stream: Stream, frame_data: dict) -> None:
        self.pipeline.create_frame(stream, frame_data)

    def create_frames(self, stream: Stream, frame_generator,
                      rate: float = None) -> None:
        """Spawn the frame-generator thread for a DataSource element
        (reference pipeline.py:365-416)."""
        window = int(self.get_parameter("frame_window", 16, stream))
        handle = FrameGeneratorHandle(
            self, stream, frame_generator, rate=rate, frame_window=window)
        self._generators[stream.stream_id] = handle
        handle.start()

    def stop_frame_generation(self, stream_id) -> None:
        handle = self._generators.pop(stream_id, None)
        if handle:
            handle.terminate()

    def throttle_frame_generation(self, stream_id, rate) -> None:
        """Backpressure sibling of stop_frame_generation: cap this
        stream's generator at `rate` frames/sec (rate <= 0 lifts the
        cap).  Driven by the serving gateway's `(throttle stream rate)`
        control message when downstream replicas saturate -- a slowed
        source beats a shed frame."""
        handle = self._generators.get(stream_id)
        if handle:
            handle.set_rate(rate)

    # -- parameters (reference pipeline.py:422-456) ------------------------

    def get_parameter(self, name: str, default=None, stream: Stream = None):
        """Resolution order: stream "Element.name"-scoped -> stream ->
        element share/definition -> pipeline share/definition -> default."""
        if stream is not None:
            scoped = f"{self.definition.name}.{name}"
            if scoped in stream.parameters:
                return stream.parameters[scoped]
            if name in stream.parameters:
                return stream.parameters[name]
        if name in self.share:
            return self.share[name]
        if self.pipeline is not None:
            pipeline_share = getattr(self.pipeline, "share", {})
            if name in pipeline_share:
                return pipeline_share[name]
            pipeline_definition = getattr(self.pipeline, "definition", None)
            if (pipeline_definition is not None
                    and name in pipeline_definition.parameters):
                return pipeline_definition.parameters[name]
        return default

    def set_parameter(self, name: str, value) -> None:
        if self.ec_producer is not None:
            self.ec_producer.update(name, value)
        else:
            self.share[name] = value

    def resolve_error_policy(self, stream: Stream = None) -> ErrorPolicy:
        """The element's effective error policy for `stream` (resolved
        only on the error path -- the no-fault hot path never pays the
        parameter lookups)."""
        on_error = str(self.get_parameter(
            "on_error", ERROR_POLICIES[0], stream)
            or ERROR_POLICIES[0]).lower()
        if on_error not in ERROR_POLICIES:
            _LOGGER.warning("%s: unknown on_error %r; using stop_stream",
                            self.definition.name, on_error)
            on_error = ERROR_POLICIES[0]
        max_retries = parse_int(
            self.get_parameter("max_retries", DEFAULT_MAX_RETRIES,
                               stream), DEFAULT_MAX_RETRIES)
        backoff_ms = parse_float(
            self.get_parameter("retry_backoff_ms",
                               DEFAULT_RETRY_BACKOFF_MS, stream),
            DEFAULT_RETRY_BACKOFF_MS)
        return ErrorPolicy(on_error, max(max_retries, 0),
                           max(backoff_ms, 0.0) / 1000.0)

    def stop(self) -> None:
        for handle in list(self._generators.values()):
            handle.terminate()
        self._generators.clear()
        super().stop()


class AsyncHostElement(PipelineElement):
    """PipelineElement whose work runs on a WORKER THREAD while the frame
    parks (StreamEvent.PENDING) -- the host-boundary counterpart of a
    remote hop.

    Device->host readbacks (token decode, image sinks) carry a fixed
    device-link round-trip (~100 ms on tunneled TPUs); run inline on the
    event loop they serialize the whole pipeline.  Subclasses implement
    process_async(stream, **inputs) -> dict (worker thread, blocking I/O
    welcome); the frame resumes through the pipeline mailbox when it
    returns, so other frames flow through the graph meanwhile.  An
    exception in process_async releases the frame as an error (no leak).
    Worker concurrency: the "workers" parameter (default 2) bounds
    simultaneous readbacks per element.
    """

    _executor = None

    def process_async(self, stream: Stream, **inputs) -> dict:
        raise NotImplementedError

    def _get_executor(self):
        if self._executor is None:
            from concurrent.futures import ThreadPoolExecutor
            self._executor = ThreadPoolExecutor(
                max_workers=int(self.get_parameter("workers", 2)),
                thread_name_prefix=f"async-{self.definition.name}")
        return self._executor

    def process_frame(self, stream: Stream, **inputs) -> tuple:
        frame_id = stream.current_frame_id
        stream_id = stream.stream_id
        pipeline = self.pipeline

        node = self.definition.name  # responses name their node so
        # sibling branches can be in flight concurrently

        def work():
            start = time.perf_counter()
            try:
                outputs = self.process_async(stream, **inputs)
                pipeline.post_message("process_frame_response", [
                    {"stream_id": stream_id, "frame_id": frame_id,
                     "node": node,
                     "time": time.perf_counter() - start},
                    outputs or {}])
            except Exception as error:
                _LOGGER.error("%s: async work failed: %s",
                              self.definition.name, error)
                pipeline.post_message("process_frame_response", [
                    {"stream_id": stream_id, "frame_id": frame_id,
                     "node": node, "event": "error"}, {}])

        self._get_executor().submit(work)
        return StreamEvent.PENDING, None

    def stop(self) -> None:
        if self._executor is not None:
            self._executor.shutdown(wait=False, cancel_futures=True)
            self._executor = None
        super().stop()

