# Binary tensor transfer plane for CROSS-PROCESS pipeline hops.
#
# The reference moves tensors between processes as base64/zlib text through
# the MQTT broker (reference: src/aiko_services/examples/pipeline/
# elements.py:298-324 PE_DataEncode/Decode; elements/media/audio_io.py:
# 520-560 PE_RemoteSend binary topics, enabled by process.py:180-189).
# Routing bulk data through a broker caps throughput at the broker.
#
# Here the data plane is split from the control plane (SURVEY.md 5,
# "Distributed communication backend"): the broker carries only a small
# JSON DESCRIPTOR {host, port, key, dtype, shape}; the bytes ride a direct
# TCP connection between the producing and consuming processes.  Within a
# mesh, sharded compute never touches this path (XLA collectives over
# ICI/DCN); the transfer plane covers pipeline-stage hand-off between
# framework Processes on one or many hosts.
#
# Protocol (request/response, PIPELINED on one connection):
#   client -> server: 32-byte hex key + "\n"
#   server -> client: 8-byte big-endian length + raw array bytes
#                     (length 0 = unknown/expired key)
# dtype/shape travel in the descriptor, so the wire carries nothing but
# the buffer.  A client may send further keys on the same connection
# after reading each response (fetch_many batches a whole descriptor
# tree -- a warm-start weight hand-off, a KV-block migration -- into
# ONE connection per peer instead of one TCP handshake per leaf); a
# client that closes after one response gets the historical
# one-request-per-connection behavior.
#
# Failure contract: fetch() raises TransferError (a ValueError) on any
# network fault and KeyError on expired/consumed keys -- both inside the
# pipeline engine's undecodable-frame handling, so a dead producer drops
# the frame instead of killing the consumer's event loop.

from __future__ import annotations

import os
import socket
import struct
import threading
import time
import uuid

import numpy as np

from ..faults import get_injector
from ..observe.metrics import get_registry

__all__ = [
    "TensorTransferServer", "TransferError", "fetch", "fetch_many",
    "get_transfer_server", "transfer_enabled", "transfer_threshold",
    "reset_transfer_server", "reset_circuits", "transfer_circuit_ms",
]

_HEADER = struct.Struct("!Q")
_KEY_BYTES = 32  # uuid4().hex
_PURGE_INTERVAL = 5.0

TENSOR_REF_KEY = "__tensorref__"


class TransferError(ValueError):
    """A transfer-plane fetch failed (producer unreachable, stream cut).
    Subclasses ValueError so pipeline frame decoding treats it as an
    undecodable frame (dropped + logged), never a crashed handler."""


def transfer_enabled() -> bool:
    """Kill switch: AIKO_TRANSFER=0 forces every cross-process tensor
    back onto the inline base64 codec path."""
    return os.environ.get("AIKO_TRANSFER", "1") not in ("0", "false")


def transfer_threshold() -> int:
    """Arrays at or above this many bytes ride the transfer plane;
    smaller values stay inline in the control message (a descriptor +
    round-trip costs more than a small payload)."""
    return int(os.environ.get("AIKO_TRANSFER_THRESHOLD", str(1 << 16)))


def transfer_timeout() -> float:
    """Socket timeout for fetches.  Fetches run on the consumer's event
    loop, so this bounds how long one lost producer can stall the
    process; keep it well under stream grace leases."""
    return float(os.environ.get("AIKO_TRANSFER_TIMEOUT", "10"))


def transfer_retries() -> int:
    """Network-fault fetch attempts beyond the first.  A producer
    restart, a dropped TCP handshake, or a transient route flap is the
    steady state at fleet scale; one or two quick retries recover the
    frame where the old fail-fast contract dropped it.  Expired keys
    (KeyError) are never retried -- a consumed key will not come back."""
    return int(os.environ.get("AIKO_TRANSFER_RETRIES", "2"))


def transfer_retry_backoff() -> float:
    """Base retry backoff seconds (doubles per attempt)."""
    return float(os.environ.get("AIKO_TRANSFER_RETRY_MS", "50")) / 1000.0


def transfer_linger() -> float:
    """How long a key stays fetchable AFTER its first fetch.  Broker
    redelivery or a second subscriber on the hop topic (monitoring,
    debug taps) may fetch the same descriptor; dropping the key on first
    read would turn those into lost frames.  Kept SHORT: every delivered
    tensor stays resident on the producer for the linger window, so at
    steady-state streaming (frames/s x bytes/frame) the default bounds
    extra memory to a few seconds' worth of traffic; redelivery resolves
    well inside that."""
    return float(os.environ.get("AIKO_TRANSFER_LINGER", "5"))


def transfer_circuit_ms() -> float:
    """Per-peer circuit-breaker window in milliseconds (0 disables).
    A peer that exhausts a fetch's whole retry budget is marked dead
    for this window; until it heals, every fetch/fetch_many against it
    FAILS FAST with TransferError instead of burning the full
    AIKO_TRANSFER_RETRIES x AIKO_TRANSFER_RETRY_MS budget on the
    caller's event loop -- adoption and checkpoint-restore failures
    drop straight to their local-re-prefill fallback."""
    return float(os.environ.get("AIKO_TRANSFER_CIRCUIT_MS", "2000"))


# (host, port) -> monotonic deadline until which the peer is presumed
# dead.  Any SUCCESSFUL connection (including an expired-key reply:
# the peer answered) closes the circuit early.
_CIRCUITS: dict[tuple, float] = {}
_CIRCUIT_LOCK = threading.Lock()


def _circuit_open(address: tuple) -> bool:
    if not _CIRCUITS:
        return False  # lock-free fast path for the healthy fleet
    with _CIRCUIT_LOCK:
        deadline = _CIRCUITS.get(address)
        if deadline is None:
            return False
        if time.monotonic() >= deadline:
            del _CIRCUITS[address]
            return False
        return True


def _trip_circuit(address: tuple) -> None:
    window = transfer_circuit_ms()
    if window <= 0:
        return
    with _CIRCUIT_LOCK:
        _CIRCUITS[address] = time.monotonic() + window / 1000.0
    get_registry().counter("transfer.peer_open_circuits").inc()


def _close_circuit(address: tuple) -> None:
    if not _CIRCUITS:
        return
    with _CIRCUIT_LOCK:
        _CIRCUITS.pop(address, None)


def _circuit_fast_fail(address: tuple) -> None:
    get_registry().counter("transfer.circuit_fast_fails").inc()
    raise TransferError(
        f"transfer circuit open to {address[0]}:{address[1]} (peer "
        f"marked dead for {transfer_circuit_ms():g} ms after "
        f"exhausting its retry budget)")


def reset_circuits() -> None:
    with _CIRCUIT_LOCK:
        _CIRCUITS.clear()


def _advertised_host() -> str:
    """The address peers should dial: env override, else this host's
    outbound interface (UDP connect trick -- no packets sent), else the
    resolved hostname, else loopback (single-host deployments)."""
    override = os.environ.get("AIKO_TRANSFER_HOST")
    if override:
        return override
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as probe:
            probe.connect(("10.255.255.255", 1))
            address = probe.getsockname()[0]
        if address and not address.startswith("127."):
            return address
    except OSError:
        pass
    try:
        address = socket.gethostbyname(socket.gethostname())
        if address and not address.startswith("127."):
            return address
    except OSError:
        pass
    return "127.0.0.1"


def _resolve_dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # bfloat16 & friends (ships with jax)
        return np.dtype(getattr(ml_dtypes, name))


class TensorTransferServer:
    """Per-process tensor side-channel: offered arrays are served by key
    until ttl expires.  A key stays valid for transfer_linger() seconds
    after its first fetch (re-fetchable across broker redelivery or a
    second hop-topic subscriber), then expires; expiry is enforced both
    on offer() and periodically by the accept loop.  The listen interface
    defaults to all interfaces; set AIKO_TRANSFER_BIND to restrict (the
    key is otherwise the only access control)."""

    def __init__(self, host: str | None = None, port: int = 0,
                 ttl: float = 300.0):
        self.ttl = float(ttl)
        self._store: dict[str, tuple[float, np.ndarray]] = {}
        self._lock = threading.Lock()
        self._listener = self._make_listener(int(port))
        self.port = self._listener.getsockname()[1]
        self.host = host or _advertised_host()
        self._closed = False
        self._thread = threading.Thread(
            target=self._accept_loop, name="tensor_transfer", daemon=True)
        self._thread.start()

    @staticmethod
    def _make_listener(port: int) -> socket.socket:
        listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        bind_host = os.environ.get("AIKO_TRANSFER_BIND", "0.0.0.0")
        listener.bind((bind_host, port))
        listener.listen(16)
        listener.settimeout(_PURGE_INTERVAL)
        return listener

    # -- producer side -------------------------------------------------

    def offer(self, array) -> dict:
        """Stage an array for remote fetch; returns its descriptor."""
        array = np.ascontiguousarray(np.asarray(array))
        key = uuid.uuid4().hex
        with self._lock:
            self._store[key] = (time.monotonic() + self.ttl, array)
        metrics = get_registry()
        metrics.counter("transfer.offers").inc()
        metrics.counter("transfer.offered_bytes").inc(array.nbytes)
        self._purge()
        return {"host": self.host, "port": self.port, "key": key,
                "dtype": str(array.dtype), "shape": list(array.shape)}

    def _purge(self):
        now = time.monotonic()
        with self._lock:
            expired = [key for key, (deadline, _) in self._store.items()
                       if deadline < now]
            for stale in expired:
                del self._store[stale]

    # -- server side ---------------------------------------------------

    def _accept_loop(self):
        while not self._closed:
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                self._purge()  # unfetched arrays die on schedule
                continue
            except OSError:
                if self._closed:
                    return  # deliberate close()
                # UNEXPECTED listener death (fd exhaustion, an injected
                # kill, a stack reset): the advertised (host, port) is
                # baked into every outstanding descriptor, so restart
                # the accept loop on the SAME port instead of silently
                # turning every future fetch into a dropped frame
                if not self._restart_listener():
                    return
                continue
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _restart_listener(self) -> bool:
        get_registry().counter("transfer.listener_restarts").inc()
        try:
            self._listener.close()
        except OSError:
            pass
        for attempt in range(5):
            if self._closed:
                return False
            try:
                listener = self._make_listener(self.port)
            except OSError:
                time.sleep(0.1 * (2.0 ** attempt))  # port still in TIME_WAIT
                continue
            with self._lock:
                if self._closed:
                    # close() raced the rebind: a fresh listener behind
                    # a closed server would leak the socket (and hold a
                    # pinned port against the replacement singleton)
                    listener.close()
                    return False
                self._listener = listener
            return True
        # give up with REAL close() semantics: _closed must flip so
        # get_transfer_server() replaces this instance instead of
        # handing out descriptors nobody will ever serve
        self.close()
        return False

    def _handle(self, conn: socket.socket):
        try:
            conn.settimeout(transfer_timeout())
            injector = get_injector()
            if injector is not None:
                # seeded per-connection stall (faults.py transfer_stall):
                # a wedged keeper/producer that accepts but never
                # answers -- the client's socket timeout, not this
                # sleep, bounds the caller
                stall = injector.transfer_stall()
                if stall > 0:
                    time.sleep(stall)
            # the pipelined protocol writes a small header before each
            # buffer; Nagle + delayed ACK would turn every round trip
            # into a ~40 ms stall
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            get_registry().counter("transfer.connections").inc()
            # pipelined request loop: serve keys until the client closes
            # (a single-fetch client closes after one response, which is
            # the historical contract; fetch_many keeps the connection
            # open across a whole descriptor tree)
            while True:
                request = b""
                while not request.endswith(b"\n"):
                    chunk = conn.recv(_KEY_BYTES + 1 - len(request))
                    if not chunk:
                        return
                    request += chunk
                key = request.strip().decode("ascii", "replace")
                now = time.monotonic()
                with self._lock:
                    entry = self._store.get(key)
                    if entry is not None and entry[0] < now:
                        del self._store[key]
                        entry = None
                    elif entry is not None:
                        # first fetch starts the linger clock; later
                        # fetches within the window reuse the same
                        # (shortened) deadline
                        deadline = min(entry[0], now + transfer_linger())
                        self._store[key] = (deadline, entry[1])
                if entry is None:
                    conn.sendall(_HEADER.pack(0))
                    continue
                _, array = entry
                try:  # zero-copy stream of the contiguous buffer
                    view = memoryview(array).cast("B")
                except (TypeError, ValueError, BufferError):
                    view = array.tobytes()  # exotic dtypes w/o buffers
                conn.sendall(_HEADER.pack(array.nbytes))
                conn.sendall(view)
                get_registry().counter(
                    "transfer.served_bytes").inc(array.nbytes)
        except OSError:
            pass
        finally:
            conn.close()

    def close(self):
        self._closed = True
        with self._lock:
            # under the lock: _restart_listener swaps self._listener
            # under the same lock, so the close always hits the LIVE
            # listener, never a just-replaced stale reference
            try:
                self._listener.close()
            except OSError:
                pass
            self._store.clear()


def fetch(descriptor: dict, timeout: float | None = None,
          retries: int | None = None) -> np.ndarray:
    """Dial the descriptor's producer and pull the raw buffer,
    retrying network faults with exponential backoff (the linger window
    keeps the key fetchable across the retry span).

    Returns a WRITABLE array (received into a fresh bytearray).  Raises
    KeyError for consumed/expired keys (never retried), TransferError
    after `retries` + 1 failed network attempts.  Counters:
    `transfer.fetch_errors` counts every FAILED ATTEMPT,
    `transfer.fetch_retries` every retry taken -- on a run where every
    retry recovered, the two reconcile (errors == retries)."""
    if timeout is None:
        timeout = transfer_timeout()
    if retries is None:
        retries = transfer_retries()
    address = (descriptor["host"], int(descriptor["port"]))
    if _circuit_open(address):
        _circuit_fast_fail(address)
    metrics = get_registry()
    fetch_start = time.perf_counter()
    backoff = transfer_retry_backoff()
    injector = get_injector()
    attempt = 0
    while True:
        try:
            if injector is not None and injector.fetch_drop():
                raise OSError("injected socket drop (fetch_drop)")
            with socket.create_connection(address,
                                          timeout=timeout) as conn:
                conn.settimeout(timeout)
                conn.setsockopt(socket.IPPROTO_TCP,
                                socket.TCP_NODELAY, 1)
                conn.sendall(descriptor["key"].encode("ascii") + b"\n")
                header = _recv_exact(conn, _HEADER.size)
                (length,) = _HEADER.unpack(header)
                if length == 0:
                    metrics.counter("transfer.fetch_expired").inc()
                    # the peer ANSWERED: it is alive, the key is gone
                    _close_circuit(address)
                    raise KeyError(
                        f"tensor {descriptor['key']} expired at "
                        f"{address[0]}:{address[1]}")
                raw = _recv_exact(conn, length)
            break
        except OSError as error:
            metrics.counter("transfer.fetch_errors").inc()
            if attempt >= retries:
                _trip_circuit(address)
                raise TransferError(
                    f"tensor fetch from {address[0]}:{address[1]} "
                    f"failed after {attempt + 1} attempts: "
                    f"{error}") from error
            metrics.counter("transfer.fetch_retries").inc()
            time.sleep(backoff * (2.0 ** attempt))
            attempt += 1
    _close_circuit(address)
    metrics.counter("transfer.fetches").inc()
    metrics.counter("transfer.fetched_bytes").inc(length)
    metrics.histogram("transfer.fetch_s").record(
        time.perf_counter() - fetch_start)
    array = np.frombuffer(raw, dtype=_resolve_dtype(descriptor["dtype"]))
    return array.reshape(descriptor["shape"])


def fetch_many(descriptors, timeout: float | None = None,
               retries: int | None = None) -> list:
    """Fetch a whole batch of descriptors with ONE connection per peer,
    pipelining key requests over it -- the descriptor-tree fast path
    (warm-start weight hand-off, prefill->decode KV migration).  A
    per-leaf fetch() pays a TCP handshake per tensor; at KV-block
    granularity that is dozens of round trips per prompt, and the
    handshake -- not the bytes -- dominates.  Here a prompt's KV
    migrates in one connection per producing peer.

    Returns arrays in input order.  Raises KeyError on the first
    consumed/expired key (never retried) and TransferError after the
    retry budget; a connection cut mid-batch retries only the keys not
    yet received.  `transfer.batched_fetches` counts connections this
    path opened; `transfer.fetches`/`fetched_bytes` count per-leaf as
    on the single-fetch path, so the two reconcile."""
    if timeout is None:
        timeout = transfer_timeout()
    if retries is None:
        retries = transfer_retries()
    metrics = get_registry()
    injector = get_injector()
    results: list = [None] * len(descriptors)
    by_peer: dict[tuple, list] = {}
    for index, descriptor in enumerate(descriptors):
        address = (descriptor["host"], int(descriptor["port"]))
        by_peer.setdefault(address, []).append(index)
    fetch_start = time.perf_counter()
    for address, indices in by_peer.items():
        if _circuit_open(address):
            _circuit_fast_fail(address)
        backoff = transfer_retry_backoff()
        attempt = 0
        remaining = list(indices)
        while remaining:
            try:
                if injector is not None and injector.fetch_drop():
                    raise OSError("injected socket drop (fetch_drop)")
                with socket.create_connection(
                        address, timeout=timeout) as conn:
                    conn.settimeout(timeout)
                    # a batch alternates small key writes with reads:
                    # Nagle + delayed ACK would cost ~40 ms per leaf
                    conn.setsockopt(socket.IPPROTO_TCP,
                                    socket.TCP_NODELAY, 1)
                    metrics.counter("transfer.batched_fetches").inc()
                    while remaining:
                        index = remaining[0]
                        descriptor = descriptors[index]
                        conn.sendall(
                            descriptor["key"].encode("ascii") + b"\n")
                        header = _recv_exact(conn, _HEADER.size)
                        (length,) = _HEADER.unpack(header)
                        if length == 0:
                            metrics.counter(
                                "transfer.fetch_expired").inc()
                            _close_circuit(address)
                            raise KeyError(
                                f"tensor {descriptor['key']} expired "
                                f"at {address[0]}:{address[1]}")
                        raw = _recv_exact(conn, length)
                        array = np.frombuffer(
                            raw, dtype=_resolve_dtype(
                                descriptor["dtype"]))
                        results[index] = array.reshape(
                            descriptor["shape"])
                        metrics.counter("transfer.fetches").inc()
                        metrics.counter(
                            "transfer.fetched_bytes").inc(length)
                        remaining.pop(0)
            except OSError as error:
                metrics.counter("transfer.fetch_errors").inc()
                if attempt >= retries:
                    _trip_circuit(address)
                    raise TransferError(
                        f"batched tensor fetch from "
                        f"{address[0]}:{address[1]} failed after "
                        f"{attempt + 1} attempts with "
                        f"{len(remaining)} leaves left: "
                        f"{error}") from error
                metrics.counter("transfer.fetch_retries").inc()
                time.sleep(backoff * (2.0 ** attempt))
                attempt += 1
        _close_circuit(address)
    metrics.histogram("transfer.fetch_s").record(
        time.perf_counter() - fetch_start)
    return results


def _recv_exact(conn: socket.socket, count: int) -> bytearray:
    buffer = bytearray(count)
    view = memoryview(buffer)
    received = 0
    while received < count:
        chunk = conn.recv_into(view[received:], count - received)
        if not chunk:
            raise ConnectionError(
                "tensor transfer connection closed mid-stream")
        received += chunk
    return buffer


_SERVER: TensorTransferServer | None = None
_SERVER_LOCK = threading.Lock()


def get_transfer_server() -> TensorTransferServer:
    """Lazily started per-process singleton (first large tensor to cross
    a process boundary brings the listener up)."""
    global _SERVER
    with _SERVER_LOCK:
        if _SERVER is None or _SERVER._closed:
            _SERVER = TensorTransferServer()
        return _SERVER


def reset_transfer_server():
    global _SERVER
    with _SERVER_LOCK:
        if _SERVER is not None:
            _SERVER.close()
            _SERVER = None
