# Stream model: streams of frames flowing through a pipeline graph.
#
# Capability parity with the reference stream model (reference:
# src/aiko_services/main/stream.py:25-98): StreamEvent return codes from
# element process_frame calls, StreamState for the stream lifecycle, Frame as
# the per-frame continuation (accumulated outputs in "swag", pause point for
# remote hops, per-element metrics), and Stream as the per-stream context
# (parameters, response routing, variables).
#
# TPU-first difference: swag values are arbitrary Python objects INCLUDING
# jax.Array -- in-process element hand-off is a dict insert, never a
# serialization (SURVEY.md section 2.4).  Stream context is always passed
# explicitly; there is no thread-local stream state (reference
# pipeline.py:584-610 is a design smell SURVEY.md section 7 says to drop).

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum

__all__ = ["StreamEvent", "StreamState", "Frame", "Stream",
           "DEFAULT_STREAM_ID", "FIRST_FRAME_ID"]

DEFAULT_STREAM_ID = "*"   # reference stream.py:30
FIRST_FRAME_ID = 0        # reference stream.py:31


class StreamEvent(Enum):
    OKAY = "okay"
    STOP = "stop"
    ERROR = "error"
    DROP_FRAME = "drop_frame"
    # frame parks at this element (work continues off the event loop --
    # AsyncHostElement worker or remote hop); a process_frame_response
    # resumes it.  Other frames keep flowing meanwhile.
    PENDING = "pending"
    USER = "user"


class StreamState(Enum):
    RUN = "run"
    STOP = "stop"
    ERROR = "error"
    DROP_FRAME = "drop_frame"


@dataclass
class Frame:
    frame_id: int = FIRST_FRAME_ID
    swag: dict = field(default_factory=dict)
    metrics: dict = field(default_factory=dict)
    # remote hops park the frame EXCLUSIVELY here (the reply cannot name
    # its node); local async/micro parks use pending_nodes instead so
    # sibling branches keep executing (fan-out concurrency -- the
    # reference executes branches sequentially, pipeline.py:1037-1092)
    paused_pe_name: str | None = None
    executed: set = field(default_factory=set)       # nodes completed
    pending_nodes: set = field(default_factory=set)  # nodes in flight
    # armed (a Lease) when an unroutable response leaves the frame's
    # attribution in doubt: releases the frame if nothing resumes it;
    # park_doubtful accumulates the parks in doubt (unions across
    # re-arms, pruned of resumed nodes at expiry)
    park_watchdog: object = None
    park_doubtful: set = field(default_factory=set)
    # True once a remote hop has parked this frame: un-named replies can
    # then be delayed duplicates of the remote's, so they are never
    # auto-routed to a local park
    had_remote_park: bool = False
    # per-frame trace (observe.FrameTrace) minted at stream ingress when
    # pipeline telemetry is enabled; None otherwise (every tracing hook
    # is then a single is-None check)
    trace: object = None
    # per-node retry attempts under the `on_error: retry` policy (lazily
    # built on first retry -- the no-fault hot path never allocates it)
    retries: dict | None = None
    # armed (a Lease) when the stream resolves a `frame_deadline`: the
    # frame is released as an error when the deadline passes with work
    # still in flight (a dead remote hop / lost reply must not leak the
    # frame's backpressure slot until the stream lease expires)
    deadline_lease: object = None


@dataclass
class Stream:
    stream_id: str = DEFAULT_STREAM_ID
    frame_id: int = FIRST_FRAME_ID          # next frame id to assign
    graph_path: str | None = None
    frames: dict = field(default_factory=dict)   # frame_id -> Frame
    parameters: dict = field(default_factory=dict)
    queue_response: object = None
    topic_response: str | None = None
    state: StreamState = StreamState.RUN
    variables: dict = field(default_factory=dict)  # per-element stream state
    pending: int = 0    # frames posted but not yet finished (backpressure)
    stop_requested: bool = False   # graceful stop: destroy when pending==0
    destroying: bool = False       # destroy_stream in progress (reentrancy)
    # the frame_id the engine is currently executing an element for --
    # explicit context (the reference used thread-locals, pipeline.py:
    # 584-610); AsyncHostElement uses it to address its resume message
    current_frame_id: int | None = None
    # error-budget window (lazily a deque of monotonic timestamps): when
    # `error_budget` errors land within `error_window` seconds the
    # stream is quarantined (destroyed with StreamState.ERROR) instead
    # of flapping forever under drop_frame/retry policies
    error_times: object = None

    def to_dict(self) -> dict:
        return {"stream_id": self.stream_id, "frame_id": self.frame_id}
