from .stream import (                                          # noqa: F401
    Stream, Frame, StreamEvent, StreamState, DEFAULT_STREAM_ID,
    FIRST_FRAME_ID)
from .definition import (                                      # noqa: F401
    PipelineDefinition, ElementDefinition, DefinitionError,
    parse_pipeline_definition, validate_pipeline_definition)
from .element import (                                         # noqa: F401
    ErrorPolicy, PipelineElement, AsyncHostElement, FrameGeneratorHandle)
from .pipeline import Pipeline, RemoteElement, create_pipeline  # noqa: F401
from .tensors import (                                         # noqa: F401
    encode_frame_data, decode_frame_data, encode_value, decode_value)
from .transfer import (                                        # noqa: F401
    TensorTransferServer, fetch as fetch_tensor, get_transfer_server,
    reset_transfer_server)
from .tpu_element import (                                     # noqa: F401
    ComputeElement, bucket_length, pad_axis_to)
