# Tensor-aware frame-data codec for CROSS-PROCESS hops only.
#
# In-process, swag values (including jax.Array) pass by reference and never
# touch this codec.  When a frame crosses a process boundary:
#
#   large arrays (>= AIKO_TRANSFER_THRESHOLD, default 64 KiB) are staged
#   on the per-process TensorTransferServer and travel as a tiny JSON
#   DESCRIPTOR -- the control plane never carries bulk data (SURVEY.md 5;
#   the reference pushed base64 tensors through the broker:
#   src/aiko_services/examples/pipeline/elements.py:298-324, audio binary
#   topics audio_io.py:520-560 / process.py:180-189);
#
#   small values stay inline as base64 .npy blobs -- a descriptor +
#   socket round-trip costs more than the payload.
#
# Within a mesh, sharded compute bypasses both paths entirely (XLA
# collectives over ICI/DCN -- the parallel/ data plane).

from __future__ import annotations

import base64
import io
import json
import zlib

import numpy as np

from .transfer import (
    TENSOR_REF_KEY, fetch, get_transfer_server, transfer_enabled,
    transfer_threshold)

__all__ = ["encode_frame_data", "decode_frame_data", "encode_value",
           "decode_value"]

_NDARRAY_KEY = "__ndarray__"
_COMPRESS_THRESHOLD_BYTES = 4096


def encode_value(value):
    if hasattr(value, "__array__") and not isinstance(
            value, (bool, int, float, str, list, tuple, dict)):
        array = np.asarray(value)
        if transfer_enabled() and array.nbytes >= transfer_threshold():
            return {TENSOR_REF_KEY: get_transfer_server().offer(array)}
        buffer = io.BytesIO()
        np.save(buffer, array, allow_pickle=False)
        raw = buffer.getvalue()
        compressed = len(raw) >= _COMPRESS_THRESHOLD_BYTES
        if compressed:
            raw = zlib.compress(raw, level=1)
        return {_NDARRAY_KEY: {
            "z": compressed,
            "data": base64.b64encode(raw).decode("ascii")}}
    if isinstance(value, dict):
        return {key: encode_value(item) for key, item in value.items()}
    if isinstance(value, (list, tuple)):
        return [encode_value(item) for item in value]
    return value


def decode_value(value):
    if isinstance(value, dict):
        if TENSOR_REF_KEY in value:
            return fetch(value[TENSOR_REF_KEY])
        if _NDARRAY_KEY in value:
            record = value[_NDARRAY_KEY]
            raw = base64.b64decode(record["data"])
            if record.get("z"):
                raw = zlib.decompress(raw)
            return np.load(io.BytesIO(raw), allow_pickle=False)
        return {key: decode_value(item) for key, item in value.items()}
    if isinstance(value, list):
        return [decode_value(item) for item in value]
    return value


def encode_frame_data(frame_data: dict) -> str:
    return json.dumps(
        {key: encode_value(value) for key, value in frame_data.items()},
        separators=(",", ":"))


def decode_frame_data(text: str) -> dict:
    return {key: decode_value(value)
            for key, value in json.loads(text).items()}
