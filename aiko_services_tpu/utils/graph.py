# Dataflow graph for pipeline definitions.
#
# Capability parity with the reference Graph (reference:
# src/aiko_services/main/utilities/graph.py:61-181): graph definitions are
# S-expressions like "(PE_0 (PE_1 PE_3) (PE_2 PE_3))" (PE_0 fans out to PE_1
# and PE_2, both feeding PE_3); traversal yields a deterministic topological
# execution order; iterate_after() resumes execution past a node (used when a
# frame returns from a remote element); node names may carry a "local:remote"
# split for cross-pipeline paths.
#
# Implemented fresh: explicit adjacency + Kahn ordering with DFS-discovery
# order as the tie-break, so execution order is both topological and stable,
# and cycles are detected at build time (the reference would loop).

from __future__ import annotations

from .sexpr import parse

__all__ = ["Graph", "Node", "GraphError"]


class GraphError(ValueError):
    pass


class Node:
    __slots__ = ("name", "element", "properties", "successors")

    def __init__(self, name: str, element=None, properties=None):
        self.name = name
        self.element = element
        self.properties = properties or {}
        self.successors: list[str] = []

    def add_successor(self, name: str) -> None:
        if name not in self.successors:
            self.successors.append(name)

    def __repr__(self):
        return f"Node({self.name} -> {self.successors})"


class Graph:
    """DAG of named nodes with deterministic topological traversal."""

    def __init__(self, head_nodes=None):
        self._nodes: dict[str, Node] = {}
        self._head_nodes: list[str] = list(head_nodes or [])
        self._order_cache: list[str] | None = None
        self._path_cache: dict[str, list] = {}
        self._descendants_cache: dict[str, frozenset] = {}

    # -- construction -----------------------------------------------------

    @classmethod
    def traverse(cls, graph_definition, node_properties_callback=None):
        """Build a Graph from S-expression path definitions.

        graph_definition: list of path strings, e.g.
        ["(PE_0 (PE_1 PE_3) (PE_2 PE_3))"].  Each path's head becomes a head
        node.  node_properties_callback(node_name, properties) is invoked for
        nodes carrying inline properties, mirroring the reference's
        map-in/out hook (reference graph.py:115-152).
        """
        graph = cls()
        for path in graph_definition:
            command, parameters = parse(path)
            if not command:
                raise GraphError(f"Empty graph path: {path!r}")
            graph._add_subtree(command, parameters, node_properties_callback)
            if command.split(":")[0] not in graph._head_nodes:
                graph._head_nodes.append(command.split(":")[0])
        graph.topological_order()  # validates acyclicity eagerly
        return graph

    def _add_subtree(self, head, children, callback) -> str:
        head_name = self._intern(head, callback)
        for child in children:
            if isinstance(child, str):
                child_name = self._intern(child, callback)
                self._nodes[head_name].add_successor(child_name)
            elif isinstance(child, list) and child:
                child_head = child[0]
                if not isinstance(child_head, str):
                    raise GraphError(f"Bad graph node: {child!r}")
                child_name = self._add_subtree(child_head, child[1:], callback)
                self._nodes[head_name].add_successor(child_name)
            elif isinstance(child, dict):
                self._nodes[head_name].properties.update(child)
                if callback:
                    callback(head_name, child)
            else:
                raise GraphError(f"Bad graph node: {child!r}")
        self._order_cache = None
        self._path_cache.clear()
        self._descendants_cache.clear()
        return head_name

    def _intern(self, token: str, callback) -> str:
        name = token.split(":")[0]  # strip "local:remote" annotation
        if name not in self._nodes:
            self._nodes[name] = Node(name)
        node = self._nodes[name]
        if ":" in token:
            node.properties.setdefault("remote_paths", []).append(token)
            if callback:
                callback(name, {"remote": token.split(":", 1)[1]})
        return name

    def add_node(self, node: Node, head: bool = False) -> None:
        self._nodes[node.name] = node
        if head and node.name not in self._head_nodes:
            self._head_nodes.append(node.name)
        self._order_cache = None
        self._path_cache.clear()
        self._descendants_cache.clear()

    # -- queries ----------------------------------------------------------

    def get_node(self, name: str) -> Node | None:
        return self._nodes.get(name)

    def __contains__(self, name: str) -> bool:
        return name in self._nodes

    def __len__(self) -> int:
        return len(self._nodes)

    def nodes(self):
        return list(self._nodes.values())

    def node_names(self):
        return list(self._nodes)

    def head_nodes(self):
        return list(self._head_nodes)

    def predecessors(self, name: str) -> list[str]:
        return [node.name for node in self._nodes.values()
                if name in node.successors]

    def topological_order(self) -> list[str]:
        """Stable topological order: DFS-discovery order tie-break."""
        if self._order_cache is not None:
            return list(self._order_cache)
        discovery: list[str] = []
        seen = set()

        def discover(name):
            if name in seen:
                return
            seen.add(name)
            discovery.append(name)
            for successor in self._nodes[name].successors:
                discover(successor)

        for head in self._head_nodes:
            discover(head)
        for name in self._nodes:  # orphans (no head path) keep insert order
            discover(name)

        indegree = {name: 0 for name in self._nodes}
        for node in self._nodes.values():
            for successor in node.successors:
                indegree[successor] += 1
        rank = {name: index for index, name in enumerate(discovery)}
        ready = sorted(
            (name for name, degree in indegree.items() if degree == 0),
            key=rank.__getitem__)
        order: list[str] = []
        while ready:
            name = ready.pop(0)
            order.append(name)
            inserted = False
            for successor in self._nodes[name].successors:
                indegree[successor] -= 1
                if indegree[successor] == 0:
                    ready.append(successor)
                    inserted = True
            if inserted:
                ready.sort(key=rank.__getitem__)
        if len(order) != len(self._nodes):
            cyclic = [name for name in self._nodes if name not in set(order)]
            raise GraphError(f"Graph contains a cycle involving: {cyclic}")
        self._order_cache = order
        return list(order)

    def get_path(self, head: str | None = None) -> list[str]:
        """Execution order (reference graph.py:61-78).  With `head`, only
        the nodes reachable from that head -- per-stream sub-paths in
        multi-root graphs (reference pipeline_paths.json capability:
        Stream.graph_path selects which root a stream executes)."""
        order = self.topological_order()
        if head is None:
            return order
        cached = self._path_cache.get(head)
        if cached is not None:  # hot path: get_path runs once per frame
            return list(cached)
        if head not in self._nodes:
            raise GraphError(f"Unknown graph path head: {head}")
        reachable = self._reachable_from([head])
        path = [name for name in order if name in reachable]
        self._path_cache[head] = path
        return list(path)

    def _reachable_from(self, starts) -> set:
        """Transitive closure over successors, INCLUDING the start nodes."""
        reachable: set = set()
        stack = list(starts)
        while stack:
            name = stack.pop()
            if name in reachable:
                continue
            reachable.add(name)
            stack.extend(self._nodes[name].successors)
        return reachable

    def descendants(self, name: str) -> frozenset:
        """Every node strictly downstream of `name` (transitive successors,
        excluding `name` itself).  Cached -- the pipeline engine consults
        this per node per execution pass to defer descendants of in-flight
        branches (graph-order data dependencies must hold even when a
        downstream input key already exists in the swag)."""
        cached = self._descendants_cache.get(name)
        if cached is not None:
            return cached
        if name not in self._nodes:
            raise GraphError(f"Unknown node: {name}")
        result = frozenset(
            self._reachable_from(self._nodes[name].successors))
        self._descendants_cache[name] = result
        return result

    def iterate_after(self, name: str, head: str | None = None) -> list:
        """Nodes strictly after `name` in execution order (restricted to
        `head`'s sub-path when given) -- used to resume a frame when a
        remote element replies (reference graph.py:96-103)."""
        path = self.get_path(head)
        try:
            index = path.index(name)
        except ValueError:
            raise GraphError(f"Unknown node: {name}") from None
        return path[index + 1:]

    def __repr__(self):
        return f"Graph({self.topological_order()})"
