# Logging: console always; distributed log publishing is layered on by the
# runtime (a transport handler that forwards records to "{topic_path}/log",
# see runtime/process.py), giving capability parity with the reference's
# LoggingHandlerMQTT ring-buffer design (reference:
# src/aiko_services/main/utilities/logger.py:98-172) without binding the
# utility layer to any transport.

from __future__ import annotations

import logging
import os
from collections import deque

__all__ = ["get_logger", "RingBufferHandler", "DEFAULT_LOG_FORMAT"]

DEFAULT_LOG_FORMAT = "%(asctime)s %(levelname)-7s %(name)s: %(message)s"


def get_logger(name: str, level: str | None = None) -> logging.Logger:
    """Per-subsystem logger; level from AIKO_LOG_LEVEL_<NAME> then
    AIKO_LOG_LEVEL then INFO (reference logger.py:98-118)."""
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(logging.Formatter(DEFAULT_LOG_FORMAT))
        logger.addHandler(handler)
        logger.propagate = False
    env_level = (level
                 or os.environ.get(f"AIKO_LOG_LEVEL_{name.upper()}")
                 or os.environ.get("AIKO_LOG_LEVEL")
                 or "INFO")
    logger.setLevel(env_level.upper())
    return logger


class RingBufferHandler(logging.Handler):
    """Buffers records until a sink is attached, then streams through it.

    The runtime attaches a sink that publishes to the service's /log topic
    once the transport connects, flushing the buffered backlog first --
    the same connect-then-flush behavior as the reference's MQTT handler
    (reference logger.py:137-145), transport-agnostic here.
    """

    def __init__(self, capacity: int = 128):
        super().__init__()
        self._ring = deque(maxlen=capacity)
        self._sink = None
        self.setFormatter(logging.Formatter(DEFAULT_LOG_FORMAT))

    def attach_sink(self, sink) -> None:
        self._sink = sink
        while self._ring:
            self._emit_to_sink(self._ring.popleft())

    def detach_sink(self) -> None:
        self._sink = None

    def _emit_to_sink(self, text: str) -> None:
        try:
            self._sink(text)
        except Exception:  # logging must never take the process down
            pass

    def emit(self, record: logging.LogRecord) -> None:
        text = self.format(record)
        if self._sink is None:
            self._ring.append(text)
        else:
            self._emit_to_sink(text)
