# Logging: console always; distributed log publishing is layered on by the
# runtime -- every Service owns a get_service_logger() logger whose
# RingBufferHandler is given a "{topic_path}/log" publish sink when the
# transport connects (runtime/service.py), giving capability parity with the
# reference's LoggingHandlerMQTT ring-buffer design (reference:
# src/aiko_services/main/utilities/logger.py:98-172) without binding the
# utility layer to any transport.  AIKO_LOG_DISTRIBUTED=false disables
# publishing (reference AIKO_LOG_MQTT, logger.py:127).

from __future__ import annotations

import logging
import os
from collections import deque

__all__ = ["get_logger", "get_service_logger", "dispose_service_logger",
           "distributed_logging_enabled", "RingBufferHandler",
           "DEFAULT_LOG_FORMAT"]

DEFAULT_LOG_FORMAT = "%(asctime)s %(levelname)-7s %(name)s: %(message)s"


def get_logger(name: str, level: str | None = None) -> logging.Logger:
    """Per-subsystem logger; level from AIKO_LOG_LEVEL_<NAME> then
    AIKO_LOG_LEVEL then INFO (reference logger.py:98-118)."""
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(logging.Formatter(DEFAULT_LOG_FORMAT))
        logger.addHandler(handler)
        logger.propagate = False
    env_level = (level
                 or os.environ.get(f"AIKO_LOG_LEVEL_{name.upper()}")
                 or os.environ.get("AIKO_LOG_LEVEL")
                 or "INFO")
    logger.setLevel(env_level.upper())
    return logger


def distributed_logging_enabled() -> bool:
    """AIKO_LOG_DISTRIBUTED=false|0|off turns off per-service /log topic
    publishing (reference AIKO_LOG_MQTT gate, logger.py:127)."""
    value = os.environ.get("AIKO_LOG_DISTRIBUTED", "true").lower()
    return value not in ("false", "0", "off")


def get_service_logger(topic_path: str, capacity: int = 128):
    """(logger, ring_handler) pair for one service instance.

    The logger is named "aiko.service.{topic_path}" (unique per service:
    process ids are unique per OS process, service ids per Process).
    Console output always; the ring handler buffers records until the
    runtime attaches the /log publish sink at TRANSPORT connect, flushing
    the backlog first.  ring_handler is None when distributed logging is
    disabled.
    """
    logger = logging.getLogger(f"aiko.service.{topic_path}")
    ring = None
    if not logger.handlers:
        console = logging.StreamHandler()
        console.setFormatter(logging.Formatter(DEFAULT_LOG_FORMAT))
        logger.addHandler(console)
        logger.propagate = False
        if distributed_logging_enabled():
            ring = RingBufferHandler(capacity)
            logger.addHandler(ring)
    else:
        for handler in logger.handlers:
            if isinstance(handler, RingBufferHandler):
                ring = handler
    env_level = (os.environ.get("AIKO_LOG_LEVEL") or "INFO")
    logger.setLevel(env_level.upper())
    return logger, ring


def dispose_service_logger(logger: logging.Logger) -> None:
    """Release a get_service_logger() logger when its service stops:
    logging.getLogger instances live forever in the manager dict, so a
    process that churns services must reclaim handlers + ring buffers."""
    for handler in list(logger.handlers):
        logger.removeHandler(handler)
        handler.close()
    logging.Logger.manager.loggerDict.pop(logger.name, None)


class RingBufferHandler(logging.Handler):
    """Buffers records until a sink is attached, then streams through it.

    The runtime attaches a sink that publishes to the service's /log topic
    once the transport connects, flushing the buffered backlog first --
    the same connect-then-flush behavior as the reference's MQTT handler
    (reference logger.py:137-145), transport-agnostic here.
    """

    def __init__(self, capacity: int = 128):
        super().__init__()
        self._ring = deque(maxlen=capacity)
        self._sink = None
        self.setFormatter(logging.Formatter(DEFAULT_LOG_FORMAT))

    def attach_sink(self, sink) -> None:
        self._sink = sink
        while self._ring:
            self._emit_to_sink(self._ring.popleft())

    def detach_sink(self) -> None:
        self._sink = None

    def _emit_to_sink(self, text: str) -> None:
        try:
            self._sink(text)
        except Exception:  # logging must never take the process down
            pass

    def emit(self, record: logging.LogRecord) -> None:
        text = self.format(record)
        if self._sink is None:
            self._ring.append(text)
        else:
            self._emit_to_sink(text)
