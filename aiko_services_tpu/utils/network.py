# Network diagnostics (capability parity with reference
# src/aiko_services/main/utilities/network.py:8-21: psutil scan of
# listening TCP/UDP ports).

from __future__ import annotations

__all__ = ["get_network_ports_listen"]


def get_network_ports_listen() -> list[tuple[str, int, str]]:
    """[(ip, port, protocol)] for listening TCP and bound UDP sockets."""
    try:
        import psutil
    except ImportError:  # psutil optional: degrade to empty diagnostics
        return []
    results = []
    for connection in psutil.net_connections(kind="inet"):
        if connection.status == psutil.CONN_LISTEN:
            protocol = "tcp"
        elif connection.status == psutil.CONN_NONE and connection.laddr:
            protocol = "udp"
        else:
            continue
        if connection.laddr:
            results.append((connection.laddr.ip, connection.laddr.port,
                            protocol))
    return sorted(set(results))
