from .sexpr import (                                        # noqa: F401
    generate, parse, parse_list_to_dict, parse_int, parse_float,
    parse_number, ParseError)
from .graph import Graph, Node, GraphError                  # noqa: F401
from .config import (                                       # noqa: F401
    get_namespace, get_hostname, get_pid, get_transport_configuration,
    get_mqtt_configuration, get_bool_env, truthy, probe_tcp, get_mqtt_host,
    BootstrapResponder)
from .lock import DiagnosticLock                            # noqa: F401
from .lru_cache import LRUCache                             # noqa: F401
from .timeutil import (                                     # noqa: F401
    epoch_now, epoch_to_iso, iso_to_epoch, monotonic)
from .logger import (get_logger, get_service_logger,        # noqa: F401
                     dispose_service_logger,
                     distributed_logging_enabled, RingBufferHandler)
from .importer import load_module                           # noqa: F401
from .padding import bucket_length, pad_axis_to             # noqa: F401,E402
from .network import get_network_ports_listen               # noqa: F401,E402
