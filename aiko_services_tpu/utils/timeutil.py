# UTC time helpers (capability parity with reference
# src/aiko_services/main/utilities/utc_iso8601.py:63-92).

from __future__ import annotations

import time
from datetime import datetime, timezone

__all__ = ["epoch_now", "epoch_to_iso", "iso_to_epoch", "monotonic"]


def epoch_now() -> float:
    return time.time()


def monotonic() -> float:
    return time.monotonic()


def epoch_to_iso(epoch: float) -> str:
    return datetime.fromtimestamp(epoch, tz=timezone.utc).isoformat(
        timespec="milliseconds")


def iso_to_epoch(text: str) -> float:
    return datetime.fromisoformat(text).timestamp()
