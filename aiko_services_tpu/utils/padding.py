# Shape-bucketing helpers shared by the pipeline engine and the parallel
# kernels (utils so parallel/ need not import pipeline/).  Bucketing bounds
# jit's shape-keyed compilation cache for ragged streaming inputs: pad
# variable axes up to O(log(max_len)) bucket sizes instead of compiling one
# program per observed length.  No reference counterpart -- the reference
# never compiles anything (SURVEY.md 7 "hard parts": recompilation control).

from __future__ import annotations

import logging

import numpy as np

__all__ = ["bucket_length", "pad_axis_to"]

_LOGGER = logging.getLogger("aiko.padding")


def bucket_length(length: int, minimum: int = 16,
                  buckets: list | None = None) -> int:
    """Smallest allowed padded length >= length.

    With explicit buckets, pick the first bucket that fits; lengths beyond
    the last bucket fall back to power-of-two growth (never truncate).
    Otherwise round up to a power of two, floored at `minimum`.
    """
    if buckets:
        for bucket in buckets:
            if length <= bucket:
                return int(bucket)
        _LOGGER.warning(
            "length %d exceeds largest bucket %d; growing power-of-two",
            length, buckets[-1])
        minimum = int(buckets[-1])
    padded = max(int(minimum), 1)
    while padded < length:
        padded *= 2
    return padded


def pad_axis_to(array, axis: int, target: int, pad_value=0):
    """Pad `axis` up to `target` with pad_value; no-op when already there.
    Refuses to shrink -- silent truncation loses frame data."""
    current = array.shape[axis]
    if current == target:
        return array
    if current > target:
        raise ValueError(
            f"pad_axis_to cannot shrink axis {axis} from {current} to "
            f"{target}")
    widths = [(0, 0)] * array.ndim
    widths[axis] = (0, target - current)
    if isinstance(array, np.ndarray):
        return np.pad(array, widths, constant_values=pad_value)
    import jax.numpy as jnp
    return jnp.pad(array, widths, constant_values=pad_value)
