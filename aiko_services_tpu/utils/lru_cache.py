# Bounded LRU cache (capability parity with reference
# src/aiko_services/main/utilities/lru_cache.py:22-47), used for audio
# sliding windows and the recorder's per-topic ring buffers.

from __future__ import annotations

from collections import OrderedDict

__all__ = ["LRUCache"]


class LRUCache:
    def __init__(self, size: int):
        self.size = size
        self._cache = OrderedDict()

    def get(self, key, default=None):
        if key in self._cache:
            self._cache.move_to_end(key)
            return self._cache[key]
        return default

    def put(self, key, value) -> None:
        if key in self._cache:
            self._cache.move_to_end(key)
        self._cache[key] = value
        while len(self._cache) > self.size:
            self._cache.popitem(last=False)

    def delete(self, key) -> None:
        self._cache.pop(key, None)

    def keys(self):
        return list(self._cache.keys())

    def values(self):
        return list(self._cache.values())

    def items(self):
        return list(self._cache.items())

    def __len__(self):
        return len(self._cache)

    def __contains__(self, key):
        return key in self._cache
