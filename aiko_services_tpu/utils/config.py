# Environment configuration.
#
# Capability parity with the reference configuration module (reference:
# src/aiko_services/main/utilities/configuration.py:91-186): namespace, host
# identity, and transport endpoint come from AIKO_* environment variables with
# sane localhost defaults.  The TPU framework adds mesh/topology variables and
# defaults the transport to the in-process loopback broker so broker-less
# hermetic runs are the default rather than a fallback.

from __future__ import annotations

import os
import socket

__all__ = [
    "get_namespace", "get_hostname", "get_pid", "get_transport_configuration",
    "get_mqtt_configuration", "get_bool_env", "truthy", "probe_tcp",
    "get_mqtt_host",
    "BootstrapResponder",
]

DEFAULT_NAMESPACE = "aiko"
BOOTSTRAP_PORT = 4149  # reference configuration.py:168 (UDP MCU bootstrap)


def get_namespace() -> str:
    return os.environ.get("AIKO_NAMESPACE", DEFAULT_NAMESPACE)


def get_hostname() -> str:
    hostname = os.environ.get("AIKO_HOSTNAME")
    if hostname:
        return hostname
    return socket.gethostname().split(".")[0].lower()


def get_pid() -> str:
    return str(os.getpid())


def truthy(value) -> bool:
    """Normalize wire/share/env boolean forms: EC updates and S-expr
    payloads deliver strings ("true"/"false"), Python code passes bools."""
    if isinstance(value, str):
        return value.strip().lower() in ("1", "true", "yes", "on", "all")
    return bool(value)


def get_bool_env(name: str, default: bool = False) -> bool:
    value = os.environ.get(name)
    if value is None:
        return default
    return truthy(value)


def get_mqtt_configuration(port: int | None = None) -> dict:
    """MQTT endpoint settings (reference configuration.py:101-114).

    AIKO_MQTT_HOST names a broker directly (no probe -- tests and fixed
    deployments).  Otherwise, when AIKO_MQTT_HOSTS lists candidates, the
    first one answering a TCP connect probe wins (reference
    configuration.py:121-139); nothing reachable falls back to
    localhost.  `port` pins the probe/endpoint port (default
    AIKO_MQTT_PORT)."""
    if port is None:
        port = int(os.environ.get("AIKO_MQTT_PORT", "1883"))
    host = os.environ.get("AIKO_MQTT_HOST")
    if not host and os.environ.get("AIKO_MQTT_HOSTS"):
        host = get_mqtt_host(port=int(port))
    return {
        "host": host or "localhost",
        "port": int(port),
        "transport": os.environ.get("AIKO_MQTT_TRANSPORT", "tcp"),
        "username": os.environ.get("AIKO_USERNAME"),
        "password": os.environ.get("AIKO_PASSWORD"),
        "tls": get_bool_env("AIKO_MQTT_TLS"),
    }


def probe_tcp(host: str, port: int, timeout: float = 0.5) -> bool:
    """True when a TCP connect to host:port succeeds within timeout (the
    reference's broker-reachability probe, configuration.py:121-139)."""
    try:
        with socket.create_connection((host, int(port)), timeout=timeout):
            return True
    except OSError:
        return False


def get_mqtt_host(candidates: list | None = None,
                  port: int | None = None,
                  timeout: float = 0.5) -> str | None:
    """First REACHABLE broker host: AIKO_MQTT_HOST, then the comma list
    AIKO_MQTT_HOSTS, then localhost -- each verified with a TCP connect
    probe (reference configuration.py:121-139).  None when nothing
    answers (callers fall back to the loopback broker)."""
    if port is None:
        port = int(os.environ.get("AIKO_MQTT_PORT", "1883"))
    if candidates is None:
        candidates = []
        primary = os.environ.get("AIKO_MQTT_HOST")
        if primary:
            candidates.append(primary)
        extra = os.environ.get("AIKO_MQTT_HOSTS", "")
        candidates += [h.strip() for h in extra.split(",") if h.strip()]
        candidates.append("localhost")
    for host in candidates:
        if probe_tcp(host, port, timeout):
            return host
    return None


class BootstrapResponder:
    """UDP bootstrap responder for MCU-class devices (reference
    configuration.py:168-186): microcontrollers that cannot run discovery
    broadcast a datagram on BOOTSTRAP_PORT and receive the namespace +
    broker endpoint back, e.g. b"boot?" -> b"(boot aiko localhost 1883)".
    """

    def __init__(self, port: int = BOOTSTRAP_PORT,
                 mqtt_host: str | None = None, mqtt_port: int | None = None):
        import threading
        if mqtt_port is None:
            mqtt_port = int(os.environ.get("AIKO_MQTT_PORT", "1883"))
        if mqtt_host is None:
            # shared resolution ladder, probing on the PINNED port
            mqtt_host = get_mqtt_configuration(port=int(mqtt_port))["host"]
        self.mqtt_host = mqtt_host
        self.mqtt_port = int(mqtt_port)
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        # no SO_REUSEADDR: a second responder on the port must fail
        # loudly (EADDRINUSE), not silently split datagram delivery
        self._sock.bind(("0.0.0.0", int(port)))
        self._sock.settimeout(1.0)
        self.port = self._sock.getsockname()[1]
        self._alive = True
        self._thread = threading.Thread(
            target=self._serve, name="aiko_bootstrap", daemon=True)
        self._thread.start()

    def _serve(self):
        while self._alive:
            try:
                _, address = self._sock.recvfrom(512)
            except socket.timeout:
                continue
            except OSError:
                return
            reply = (f"(boot {get_namespace()} {self.mqtt_host} "
                     f"{self.mqtt_port})")
            try:
                self._sock.sendto(reply.encode("utf-8"), address)
            except OSError:
                pass

    def close(self):
        self._alive = False
        try:
            self._sock.close()
        except OSError:
            pass


def get_transport_configuration() -> dict:
    """Which control-plane transport to use.

    AIKO_TRANSPORT = loopback (default) | mqtt | null.  The loopback broker
    gives full pub/sub + retained + LWT semantics in-process, so the whole
    control plane runs hermetically; MQTT is opt-in when a real broker and
    paho-mqtt are available.
    """
    return {
        "kind": os.environ.get("AIKO_TRANSPORT", "loopback"),
        "mqtt": get_mqtt_configuration(),
    }
