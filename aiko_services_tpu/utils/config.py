# Environment configuration.
#
# Capability parity with the reference configuration module (reference:
# src/aiko_services/main/utilities/configuration.py:91-186): namespace, host
# identity, and transport endpoint come from AIKO_* environment variables with
# sane localhost defaults.  The TPU framework adds mesh/topology variables and
# defaults the transport to the in-process loopback broker so broker-less
# hermetic runs are the default rather than a fallback.

from __future__ import annotations

import os
import socket

__all__ = [
    "get_namespace", "get_hostname", "get_pid", "get_transport_configuration",
    "get_mqtt_configuration", "get_bool_env",
]

DEFAULT_NAMESPACE = "aiko"


def get_namespace() -> str:
    return os.environ.get("AIKO_NAMESPACE", DEFAULT_NAMESPACE)


def get_hostname() -> str:
    hostname = os.environ.get("AIKO_HOSTNAME")
    if hostname:
        return hostname
    return socket.gethostname().split(".")[0].lower()


def get_pid() -> str:
    return str(os.getpid())


def get_bool_env(name: str, default: bool = False) -> bool:
    value = os.environ.get(name)
    if value is None:
        return default
    return value.strip().lower() in ("1", "true", "yes", "on", "all")


def get_mqtt_configuration() -> dict:
    """MQTT endpoint settings (reference configuration.py:101-114)."""
    return {
        "host": os.environ.get("AIKO_MQTT_HOST", "localhost"),
        "port": int(os.environ.get("AIKO_MQTT_PORT", "1883")),
        "transport": os.environ.get("AIKO_MQTT_TRANSPORT", "tcp"),
        "username": os.environ.get("AIKO_USERNAME"),
        "password": os.environ.get("AIKO_PASSWORD"),
        "tls": get_bool_env("AIKO_MQTT_TLS"),
    }


def get_transport_configuration() -> dict:
    """Which control-plane transport to use.

    AIKO_TRANSPORT = loopback (default) | mqtt | null.  The loopback broker
    gives full pub/sub + retained + LWT semantics in-process, so the whole
    control plane runs hermetically; MQTT is opt-in when a real broker and
    paho-mqtt are available.
    """
    return {
        "kind": os.environ.get("AIKO_TRANSPORT", "loopback"),
        "mqtt": get_mqtt_configuration(),
    }
