# Element module loader (capability parity with reference
# src/aiko_services/main/utilities/importer.py:24-40): loads element code by
# dotted module name or by file path, memoized.

from __future__ import annotations

import importlib
import importlib.util
import sys
from pathlib import Path

__all__ = ["load_module", "unload_module"]

_MODULES_LOADED: dict[str, object] = {}


def load_module(module_descriptor: str):
    if module_descriptor in _MODULES_LOADED:
        return _MODULES_LOADED[module_descriptor]
    if module_descriptor.endswith(".py") or "/" in module_descriptor:
        path = Path(module_descriptor)
        name = path.stem
        spec = importlib.util.spec_from_file_location(name, path)
        if spec is None or spec.loader is None:
            raise ImportError(f"Cannot load module from {module_descriptor}")
        module = importlib.util.module_from_spec(spec)
        sys.modules.setdefault(name, module)
        spec.loader.exec_module(module)
    else:
        module = importlib.import_module(module_descriptor)
    _MODULES_LOADED[module_descriptor] = module
    return module


def unload_module(name: str) -> None:
    """Drop a module from BOTH import caches (sys.modules and the
    descriptor memo) so the next load_module(name) re-imports it."""
    sys.modules.pop(name, None)
    for key, module in list(_MODULES_LOADED.items()):
        if key == name or getattr(module, "__name__", None) == name:
            del _MODULES_LOADED[key]
