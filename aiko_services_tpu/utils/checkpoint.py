# Checkpoint/restore for model state and stream cursors.
#
# The reference has NO checkpointing (SURVEY.md section 5: "Checkpoint /
# resume: absent" -- its storage.py is a sqlite skeleton and the registrar
# history ring is observability, not recovery).  A TPU framework needs it:
# preemptible TPU VMs lose HBM, so element params, optimizer state, and
# per-stream frame cursors must round-trip to disk (orbax handles the
# pytree serialization, sharded arrays included).

from __future__ import annotations

import json
from pathlib import Path

from . import get_logger

__all__ = ["Checkpointer"]

_LOGGER = get_logger("checkpoint")


class Checkpointer:
    """Step-indexed pytree checkpoints + a JSON metadata sidecar.

    save(step, pytree, metadata) / restore(step=None) -> (pytree, metadata);
    keeps the newest max_to_keep steps.  Works for any JAX pytree: model
    params, optimizer state, KV caches; metadata carries small JSON state
    (stream cursors, frame ids, config echoes).
    """

    def __init__(self, directory, max_to_keep: int = 3):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.max_to_keep = max_to_keep
        import orbax.checkpoint as ocp
        self._checkpointer = ocp.PyTreeCheckpointer()

    def _step_dir(self, step: int) -> Path:
        return self.directory / f"step_{step:012d}"

    def steps(self) -> list[int]:
        found = []
        for path in self.directory.glob("step_*"):
            try:
                found.append(int(path.name.split("_", 1)[1]))
            except ValueError:
                continue
        return sorted(found)

    def save(self, step: int, pytree, metadata: dict | None = None) -> Path:
        target = self._step_dir(step)
        if target.exists():
            import shutil
            shutil.rmtree(target)
        self._checkpointer.save(target / "state", pytree)
        (target / "metadata.json").write_text(
            json.dumps(metadata or {}, sort_keys=True))
        self._prune()
        _LOGGER.info("Checkpoint saved: %s", target)
        return target

    def restore(self, step: int | None = None):
        """Returns (pytree, metadata); (None, {}) when nothing exists."""
        steps = self.steps()
        if not steps:
            return None, {}
        step = steps[-1] if step is None else step
        target = self._step_dir(step)
        pytree = self._checkpointer.restore(target / "state")
        metadata_path = target / "metadata.json"
        metadata = (json.loads(metadata_path.read_text())
                    if metadata_path.exists() else {})
        return pytree, metadata

    def latest_step(self) -> int | None:
        steps = self.steps()
        return steps[-1] if steps else None

    def _prune(self) -> None:
        import shutil
        steps = self.steps()
        while len(steps) > self.max_to_keep:
            victim = self._step_dir(steps.pop(0))
            shutil.rmtree(victim, ignore_errors=True)
