# Checkpoint/restore for model state and stream cursors.
#
# The reference has NO checkpointing (SURVEY.md section 5: "Checkpoint /
# resume: absent" -- its storage.py is a sqlite skeleton and the registrar
# history ring is observability, not recovery).  A TPU framework needs it:
# preemptible TPU VMs lose HBM, so element params, optimizer state, and
# per-stream frame cursors must round-trip to disk (orbax handles the
# pytree serialization, sharded arrays included).

from __future__ import annotations

import json
from pathlib import Path

from . import get_logger

__all__ = ["Checkpointer"]

_LOGGER = get_logger("checkpoint")


class Checkpointer:
    """Step-indexed pytree checkpoints + a JSON metadata sidecar.

    save(step, pytree, metadata) / restore(step=None) -> (pytree, metadata);
    keeps the newest max_to_keep steps.  Works for any JAX pytree: model
    params, optimizer state, KV caches; metadata carries small JSON state
    (stream cursors, frame ids, config echoes).
    """

    def __init__(self, directory, max_to_keep: int = 3):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.max_to_keep = max_to_keep
        import orbax.checkpoint as ocp
        self._checkpointer = ocp.PyTreeCheckpointer()

    def _step_dir(self, step: int) -> Path:
        return self.directory / f"step_{step:012d}"

    def steps(self) -> list[int]:
        found = []
        for path in self.directory.glob("step_*"):
            try:
                found.append(int(path.name.split("_", 1)[1]))
            except ValueError:
                continue
        return sorted(found)

    def save(self, step: int, pytree, metadata: dict | None = None) -> Path:
        """Atomic: state + metadata land in a hidden staging dir that is
        rename()d into place, so a preemption mid-save can never leave a
        half-written newest step for restore() to pick up."""
        import shutil
        import jax
        # encode metadata BEFORE the heavy state save so a non-JSON value
        # (numpy array, bytes) fails fast instead of aborting after orbax
        # has already written
        metadata_text = json.dumps(metadata or {}, sort_keys=True)
        staging = self.directory / f".staging_step_{step}"
        if staging.exists():
            shutil.rmtree(staging)
        if len(jax.tree_util.tree_leaves(pytree)) > 0:
            self._checkpointer.save(staging / "state", pytree)
        else:
            # orbax rejects empty pytrees ("Found empty item"); a
            # metadata-only checkpoint (e.g. stream cursors with no
            # ComputeElement state) is still valid -- marked explicitly so
            # restore() can tell it apart from a LOST state payload
            staging.mkdir(parents=True, exist_ok=True)
            (staging / "no_state").touch()
        (staging / "metadata.json").write_text(metadata_text)
        target = self._step_dir(step)
        if target.exists():
            shutil.rmtree(target)
        staging.rename(target)
        self._prune()
        _LOGGER.info("Checkpoint saved: %s", target)
        return target

    def restore(self, step: int | None = None):
        """Returns (pytree, metadata); (None, {}) when nothing exists.
        With step=None, falls back to older steps if the newest is
        unreadable."""
        steps = self.steps()
        if not steps:
            return None, {}
        candidates = [step] if step is not None else list(reversed(steps))
        last_error = None
        for candidate in candidates:
            target = self._step_dir(candidate)
            try:
                if (target / "state").exists():
                    pytree = self._checkpointer.restore(target / "state")
                elif (target / "no_state").exists():
                    pytree = None  # legit metadata-only checkpoint
                else:
                    # state payload lost: treat the step as corrupt so
                    # step=None falls back to an older intact step
                    raise FileNotFoundError(
                        f"state payload missing in {target}")
                metadata = json.loads(
                    (target / "metadata.json").read_text())
            except Exception as error:  # corrupt step: try the previous
                last_error = error
                _LOGGER.warning("Checkpoint %s unreadable: %s",
                                target, error)
                continue
            return pytree, metadata
        raise RuntimeError(
            f"No readable checkpoint in {self.directory}") from last_error

    def latest_step(self) -> int | None:
        steps = self.steps()
        return steps[-1] if steps else None

    def _prune(self) -> None:
        import shutil
        steps = self.steps()
        while len(steps) > self.max_to_keep:
            victim = self._step_dir(steps.pop(0))
            shutil.rmtree(victim, ignore_errors=True)
