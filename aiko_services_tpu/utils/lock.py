# Contention-diagnosing lock.
#
# Capability parity with the reference's named Lock wrapper (reference:
# src/aiko_services/main/utilities/lock.py:17-27), which logs WHO holds a
# lock when acquisition contends instead of blocking silently -- the
# poor-thread's deadlock diagnostic.  Here acquisition first tries
# non-blocking; on contention it logs the named holder (and how long it
# has held), then keeps waiting in `warn_seconds` slices, logging again
# each time a slice elapses without acquisition.

from __future__ import annotations

import threading
import time

from .logger import get_logger

__all__ = ["DiagnosticLock"]

_LOGGER = get_logger("lock")


class DiagnosticLock:
    """threading.Lock drop-in (context-manager + acquire/release) that
    names itself and reports contention with holder attribution."""

    def __init__(self, name: str, warn_seconds: float = 1.0):
        self.name = name
        self.warn_seconds = float(warn_seconds)
        self._lock = threading.Lock()
        # (holder thread name, monotonic acquire time) -- a single
        # attribute so readers see a consistent snapshot (CPython
        # attribute assignment is atomic); None = unheld
        self._held: tuple[str, float] | None = None
        self.contentions = 0  # observable in tests/diagnostics

    def _describe_holder(self) -> str:
        held = self._held
        if held is None:
            return "(just released)"
        holder, since = held
        return f"{holder} for {time.monotonic() - since:.3f} s"

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if self._lock.acquire(blocking=False):
            self._held = (threading.current_thread().name,
                          time.monotonic())
            return True
        if not blocking:
            return False
        self.contentions += 1
        waiter = threading.current_thread().name
        deadline = (None if timeout is None or timeout < 0
                    else time.monotonic() + timeout)
        while True:
            _LOGGER.warning("lock %s: contended -- held by %s (waiter: %s)",
                            self.name, self._describe_holder(), waiter)
            if deadline is None:
                slice_timeout = self.warn_seconds
            else:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                slice_timeout = min(self.warn_seconds, remaining)
            if self._lock.acquire(timeout=slice_timeout):
                self._held = (waiter, time.monotonic())
                return True

    def release(self) -> None:
        self._held = None
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        held = self._held
        holder = held[0] if held else "unheld"
        return f"DiagnosticLock({self.name}, {holder})"
