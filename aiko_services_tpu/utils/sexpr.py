# S-expression wire codec: the control-plane payload format.
#
# Capability parity with the reference codec (reference:
# src/aiko_services/main/utilities/parser.py:85-227): commands are rendered as
# "(command param ...)", keyword dictionaries as "(a: 1 b: 2)", strings with
# whitespace/parens are double-quoted, and arbitrary binary-safe payloads use
# canonical "len:data" symbols.  parse() and generate() are inverses over the
# supported value domain.
#
# This implementation is written fresh for the TPU framework: a single-pass
# byte-oriented tokenizer (the reference uses char-by-char string slicing) so
# large binary symbols (tensor descriptors) are O(n), plus typed number
# helpers.  The hot tensor path never goes through this codec -- tensors stay
# on device as jax.Array -- so this codec only ever sees control traffic.

from __future__ import annotations

__all__ = [
    "generate", "parse", "parse_list_to_dict", "parse_int", "parse_float",
    "parse_number", "ParseError",
]


class ParseError(ValueError):
    """Raised when a payload is not a well-formed S-expression."""


_QUOTE_NEEDED = set(' \t\r\n()"')


def _ascii_digits(text: str) -> bool:
    """ASCII-only digit check: str.isdigit() accepts unicode digits like
    superscripts that int() rejects, which would make the tokenizer raise
    bare ValueError (and diverge from the native parser)."""
    return bool(text) and all("0" <= ch <= "9" for ch in text)


def _atom_needs_quoting(text: str) -> bool:
    if text == "":
        return True
    if any(ch in _QUOTE_NEEDED for ch in text):
        return True
    # "12:34" would parse as a canonical "len:data" symbol -- quote it so
    # generate() and parse() stay inverses
    colon = text.find(":")
    return colon > 0 and _ascii_digits(text[:colon])


def _generate_value(value) -> str:
    if isinstance(value, bool):
        return "true" if value else "false"
    if value is None:
        return "()"
    if isinstance(value, (int, float)):
        return repr(value)
    if isinstance(value, bytes):
        data = value.decode("latin-1")
        return f"{len(data)}:{data}"
    if isinstance(value, dict):
        inner = " ".join(
            f"{key}: {_generate_value(item)}" for key, item in value.items())
        return f"({inner})"
    if isinstance(value, (list, tuple)):
        inner = " ".join(_generate_value(item) for item in value)
        return f"({inner})"
    text = str(value)
    if _atom_needs_quoting(text):
        escaped = text.replace("\\", "\\\\").replace('"', '\\"')
        return f'"{escaped}"'
    return text


def generate(command: str, parameters=()) -> str:
    """Render a command and its parameters as one S-expression payload."""
    if parameters:
        inner = " ".join(_generate_value(item) for item in parameters)
        return f"({command} {inner})"
    return f"({command})"


class _Tokenizer:
    __slots__ = ("text", "pos", "length")

    def __init__(self, text: str):
        self.text = text
        self.pos = 0
        self.length = len(text)

    def skip_whitespace(self) -> None:
        text, pos, length = self.text, self.pos, self.length
        while pos < length and text[pos] in " \t\r\n":
            pos += 1
        self.pos = pos

    def peek(self) -> str:
        return self.text[self.pos] if self.pos < self.length else ""

    def read_quoted(self) -> str:
        # positioned on the opening quote
        text, pos = self.text, self.pos + 1
        out = []
        while pos < self.length:
            ch = text[pos]
            if ch == "\\" and pos + 1 < self.length:
                out.append(text[pos + 1])
                pos += 2
                continue
            if ch == '"':
                self.pos = pos + 1
                return "".join(out)
            out.append(ch)
            pos += 1
        raise ParseError(f"Unterminated quoted string at offset {self.pos}")

    def read_atom(self) -> str:
        text, pos, length = self.text, self.pos, self.length
        start = pos
        while pos < length and text[pos] not in ' \t\r\n()"':
            ch = text[pos]
            pos += 1
            if ch == ":" and pos > start + 1:
                # Possible canonical symbol "len:data": the run before the
                # colon must be all ASCII digits.
                digits = text[start:pos - 1]
                if _ascii_digits(digits):
                    size = int(digits)
                    end = pos + size
                    if end > length:
                        raise ParseError(
                            f"Canonical symbol overruns payload at {start}")
                    self.pos = end
                    return text[pos:end]
        self.pos = pos
        return text[start:pos]


def _parse_expression(tok: _Tokenizer):
    tok.skip_whitespace()
    ch = tok.peek()
    if ch == "":
        raise ParseError("Unexpected end of payload")
    if ch == "(":
        tok.pos += 1
        items = []
        keyword_mode = False
        while True:
            tok.skip_whitespace()
            ch = tok.peek()
            if ch == "":
                raise ParseError("Unterminated list")
            if ch == ")":
                tok.pos += 1
                break
            items.append(_parse_expression(tok))
        # A list of alternating "name:" keys and values parses to a dict,
        # mirroring the reference keyword-dictionary convention.
        if items and len(items) % 2 == 0:
            keyword_mode = all(
                isinstance(items[i], str) and items[i].endswith(":")
                and len(items[i]) > 1
                for i in range(0, len(items), 2))
        if keyword_mode:
            return {
                items[i][:-1]: items[i + 1] for i in range(0, len(items), 2)}
        return items
    if ch == '"':
        return tok.read_quoted()
    return tok.read_atom()


def _parse_python(payload) -> tuple:
    if isinstance(payload, bytes):
        payload = payload.decode("latin-1")
    tok = _Tokenizer(payload)
    tok.skip_whitespace()
    if tok.peek() == "":
        return "", []
    expression = _parse_expression(tok)
    tok.skip_whitespace()
    if tok.peek() != "":
        raise ParseError(f"Trailing data at offset {tok.pos}")
    if isinstance(expression, str):
        return expression, []
    if isinstance(expression, dict):
        return "", [expression]
    if not expression:
        return "", []
    command = expression[0]
    if not isinstance(command, str):
        return "", expression
    return command, expression[1:]


# Native fast path: the C++ extension (native/sexpr_codec.cpp) parses
# byte-per-char identically; built via `python -m
# aiko_services_tpu.native.build`.  Payloads outside latin-1 (exotic
# unicode atoms) take the Python path.
try:
    from ..native import sexpr_parse_native as _parse_native
    from ..native import install_parse_error as _install_parse_error
except ImportError:  # pragma: no cover
    _parse_native = None
else:
    if _parse_native is not None:
        _install_parse_error(ParseError)


def parse(payload) -> tuple:
    """Parse one S-expression payload into (command, parameters).

    Accepts str or bytes (bytes are latin-1 decoded so canonical symbols are
    binary-safe).  A bare atom parses as (atom, []).  Returns ("", []) for an
    empty payload.
    """
    if _parse_native is not None:
        try:
            return _parse_native(payload)
        except UnicodeEncodeError:
            pass  # non-latin-1 text: python path handles full unicode
    return _parse_python(payload)


def parse_list_to_dict(items) -> dict:
    """Fold a flat [k1 v1 k2 v2 ...] list into a dict (keys lose any ':')."""
    result = {}
    for index in range(0, len(items) - 1, 2):
        key = items[index]
        if isinstance(key, str) and key.endswith(":"):
            key = key[:-1]
        result[key] = items[index + 1]
    return result


def parse_int(text, default=0) -> int:
    try:
        return int(text)
    except (TypeError, ValueError):
        return default


def parse_float(text, default=0.0) -> float:
    try:
        return float(text)
    except (TypeError, ValueError):
        return default


def parse_number(text, default=0):
    """Parse to int when possible, else float, else default."""
    try:
        return int(text)
    except (TypeError, ValueError):
        try:
            return float(text)
        except (TypeError, ValueError):
            return default
