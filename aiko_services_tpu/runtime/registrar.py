# Registrar: the service-discovery directory with primary election.
#
# Capability parity with the reference registrar (reference:
# src/aiko_services/main/registrar.py:34-357): election via the retained
# bootstrap topic "{namespace}/service/registrar" (start -> primary_search ->
# primary | secondary, promotion after a search timeout, failover when the
# primary's LWT "(primary absent)" fires); a service table fed by "(add ...)"
# / "(remove ...)" commands; death reaping from "(absent)" state notices;
# "(share response_topic filter...)" queries; and a bounded history ring.
#
# Split-brain fix (SURVEY.md section 7 hard part 6): the reference admits a
# multi-secondary election bug (reference registrar.py:54-55); here a primary
# that sees another primary's retained announcement with an EARLIER timestamp
# deterministically demotes itself.

from __future__ import annotations

from collections import deque

from ..utils import generate, parse, parse_float, get_logger, epoch_now
from .actor import Actor
from .service import (
    ServiceFields, ServiceFilter, Services, SERVICE_PROTOCOL_REGISTRAR)
from .share import ECProducer

__all__ = ["Registrar"]

_LOGGER = get_logger("registrar")
_HISTORY_RING_SIZE = 4096  # reference registrar.py:128-129
DEFAULT_SEARCH_TIMEOUT = 2.0  # reference registrar.py:139-141


class Registrar(Actor):
    def __init__(self, process, name: str = "registrar",
                 search_timeout: float = DEFAULT_SEARCH_TIMEOUT):
        super().__init__(process, name,
                         protocol=SERVICE_PROTOCOL_REGISTRAR)
        self.search_timeout = search_timeout
        self.command_aliases["share"] = "share_query"
        self.state = "start"
        self.time_started = epoch_now()
        self.services_table = Services()
        self.history_ring: deque = deque(maxlen=_HISTORY_RING_SIZE)
        self.share.update({
            "state": self.state,
            "service_count": 0,
            "time_started": repr(self.time_started),
        })

        self._boot_topic = process.topic_path_registrar_boot
        self._state_pattern = f"{process.namespace}/+/+/+/state"
        process.add_message_handler(self._boot_handler, self._boot_topic)
        self._transition("primary_search")
        process.event.add_timer_handler(
            self._search_timer, self.search_timeout)

    # -- election ----------------------------------------------------------

    def _transition(self, state: str) -> None:
        self.state = state
        if self.ec_producer:
            self.ec_producer.update("state", state)
        _LOGGER.debug("%s: state -> %s", self.topic_path, state)

    def _search_timer(self) -> None:
        self.process.event.remove_timer_handler(self._search_timer)
        if self.state == "primary_search":
            self._promote_to_primary()

    def _boot_handler(self, topic: str, payload: str) -> None:
        try:
            command, parameters = parse(payload)
        except ValueError:
            return
        if command != "primary" or not parameters:
            return
        if parameters[0] == "found":
            found_topic = parameters[1] if len(parameters) > 1 else ""
            found_time = parse_float(
                parameters[3] if len(parameters) > 3 else "0")
            if found_topic == self.topic_path:
                return
            if self.state == "primary":
                loses_tie = (found_time == self.time_started
                             and found_topic < self.topic_path)
                if found_time and (found_time < self.time_started
                                   or loses_tie):
                    _LOGGER.warning(
                        "%s: older primary %s found, demoting",
                        self.topic_path, found_topic)
                    self._demote_to_secondary()
                else:
                    # re-assert: we are the older primary
                    self.process.announce_registrar(self.topic_path)
            elif self.state in ("primary_search", "secondary"):
                self._transition("secondary")
        elif parameters[0] == "absent":
            if self.state == "secondary":
                self._transition("primary_search")
                self.process.event.add_timer_handler(
                    self._search_timer, self.search_timeout * 0.5)

    def _promote_to_primary(self) -> None:
        self.time_started = epoch_now()
        self._transition("primary")
        transport = self.process.transport
        transport.set_last_will_and_testament(
            self._boot_topic, "(primary absent)", retain=True)
        self.process.add_message_handler(
            self._service_state_handler, self._state_pattern)
        self.process.announce_registrar(self.topic_path)

    def _demote_to_secondary(self) -> None:
        self._transition("secondary")
        self.process.transport.clear_last_will_and_testament(
            self._boot_topic)
        self.process.remove_message_handler(
            self._service_state_handler, self._state_pattern)
        self.services_table = Services()
        self._update_service_count()

    # -- service table commands (arrive via actor mailbox on /in) ----------

    def add(self, topic_path, name, protocol, transport, owner, tags=None):
        if self.state != "primary":
            return
        fields = ServiceFields(topic_path, name, protocol, transport, owner,
                               tags if isinstance(tags, list) else [tags])
        self.services_table.add_service(fields)
        self.history_ring.append(("add", fields, epoch_now()))
        self._update_service_count()
        self.publish_out("add", fields.to_parameters())

    def remove(self, topic_path):
        if self.state != "primary":
            return
        removed = self.services_table.remove_service(topic_path)
        for fields in removed:
            self.history_ring.append(("remove", fields, epoch_now()))
            self.publish_out("remove", [fields.topic_path])
        if removed:
            self._update_service_count()

    def share_query(self, response_topic, topic_paths="*", name="*",
                    protocol="*", transport="*", owner="*", tags="*"):
        service_filter = ServiceFilter(topic_paths, name, protocol,
                                       transport, owner, tags)
        matches = self.services_table.filter_services(service_filter)
        publish = self.process.publish
        publish(response_topic, generate("item_count", [len(matches)]))
        for fields in matches:
            publish(response_topic, generate("add", fields.to_parameters()))
        publish(response_topic, generate("sync", [self.topic_path]))

    def history(self, response_topic, count="16"):
        count = int(parse_float(count, 16))
        entries = list(self.history_ring)[-count:]
        publish = self.process.publish
        publish(response_topic, generate("item_count", [len(entries)]))
        for command, fields, timestamp in entries:
            publish(response_topic,
                    generate("history",
                             [command, repr(timestamp)]
                             + fields.to_parameters()))

    # -- death reaping -----------------------------------------------------

    def _service_state_handler(self, topic: str, payload: str) -> None:
        try:
            command, _ = parse(payload)
        except ValueError:
            return
        if command != "absent":
            return
        service_topic_path = topic.rsplit("/state", 1)[0]
        self.remove(service_topic_path)

    def _update_service_count(self) -> None:
        if self.ec_producer:
            self.ec_producer.update(
                "service_count", len(self.services_table))

    def stop(self) -> None:
        if self.state == "primary":
            # clean handover: clear the retained announcement
            self.process.publish(self._boot_topic, "(primary absent)",
                                 retain=True)
        self.process.remove_message_handler(self._boot_handler,
                                            self._boot_topic)
        if self.state == "primary":
            self.process.remove_message_handler(
                self._service_state_handler, self._state_pattern)
        super().stop()
