# Registrar: the service-discovery directory with primary election.
#
# Capability parity with the reference registrar (reference:
# src/aiko_services/main/registrar.py:34-357): election via the retained
# bootstrap topic "{namespace}/service/registrar" (start -> primary_search ->
# primary | secondary, promotion after a search timeout, failover when the
# primary's LWT "(primary absent)" fires); a service table fed by "(add ...)"
# / "(remove ...)" commands; death reaping from "(absent)" state notices;
# "(share response_topic filter...)" queries; and a bounded history ring.
#
# Split-brain fix (SURVEY.md section 7 hard part 6): the reference admits a
# multi-secondary election bug (reference registrar.py:54-55); here a primary
# that sees another primary's retained announcement with an EARLIER timestamp
# deterministically demotes itself.
#
# The election machine itself is extracted as RetainedElection so OTHER
# singletons ride the same proven protocol: the serving gateway's
# hot-standby pair (serve/journal.py HA mode) elects its primary over a
# retained "{namespace}/gateway/{group}" topic with exactly this state
# machine -- one election implementation, one set of split-brain and
# failover semantics, two consumers.

from __future__ import annotations

from collections import deque

from ..utils import generate, parse, parse_float, get_logger, epoch_now
from .actor import Actor
from .service import (
    ServiceFields, ServiceFilter, Services, SERVICE_PROTOCOL_REGISTRAR)
from .share import ECProducer

__all__ = ["Registrar", "RetainedElection"]

_LOGGER = get_logger("registrar")
_HISTORY_RING_SIZE = 4096  # reference registrar.py:128-129
DEFAULT_SEARCH_TIMEOUT = 2.0  # reference registrar.py:139-141


class RetainedElection:
    """Primary election over ONE retained bootstrap topic (the
    registrar protocol, reference registrar.py:139-226):

      start -> primary_search    subscribe, wait `search_timeout` for a
                                 retained "(primary found ...)"
      primary_search -> primary  nothing found: promote, set the LWT
                                 "(primary absent)" (retained), announce
      primary_search/secondary   a found record for ANOTHER topic path:
        -> secondary             stand by
      secondary -> primary_search a "(primary absent)" (the primary's
                                 LWT fired, or a clean handover):
                                 re-run the search at half timeout
      primary -> secondary       a found record with an EARLIER
                                 timestamp: deterministic demotion
                                 (split-brain fix); ties break on the
                                 LOWER topic path

    The owner supplies `announce()` (publish the retained found record
    -- its payload format is the owner's), and optional on_promote /
    on_demote / on_state callbacks.  All transitions run on the
    process event-loop thread (handlers + timers)."""

    def __init__(self, process, boot_topic: str, topic_path: str,
                 announce, search_timeout: float = DEFAULT_SEARCH_TIMEOUT,
                 on_promote=None, on_demote=None, on_state=None,
                 absent_payload: str = "(primary absent)"):
        self.process = process
        self.boot_topic = boot_topic
        self.topic_path = topic_path
        self.search_timeout = search_timeout
        self.absent_payload = absent_payload
        self._announce = announce
        self._on_promote = on_promote
        self._on_demote = on_demote
        self._on_state = on_state
        self.state = "start"
        self.time_started = epoch_now()
        self._stopped = False
        process.add_message_handler(self._boot_handler, boot_topic)
        self._transition("primary_search")
        process.event.add_timer_handler(
            self._search_timer, self.search_timeout)

    def _transition(self, state: str) -> None:
        self.state = state
        if self._on_state is not None:
            self._on_state(state)
        _LOGGER.debug("%s: election state -> %s", self.topic_path, state)

    def _search_timer(self) -> None:
        self.process.event.remove_timer_handler(self._search_timer)
        if self.state == "primary_search" and not self._stopped:
            self._promote()

    def _boot_handler(self, topic: str, payload: str) -> None:
        try:
            command, parameters = parse(payload)
        except ValueError:
            return
        if command != "primary" or not parameters:
            return
        if parameters[0] == "found":
            found_topic = parameters[1] if len(parameters) > 1 else ""
            found_time = parse_float(
                parameters[3] if len(parameters) > 3 else "0")
            if found_topic == self.topic_path:
                return
            if self.state == "primary":
                loses_tie = (found_time == self.time_started
                             and found_topic < self.topic_path)
                if found_time and (found_time < self.time_started
                                   or loses_tie):
                    _LOGGER.warning(
                        "%s: older primary %s found, demoting",
                        self.topic_path, found_topic)
                    self._demote()
                else:
                    # re-assert: we are the older primary
                    self._announce()
            elif self.state in ("primary_search", "secondary"):
                self._transition("secondary")
        elif parameters[0] == "absent":
            if self.state == "secondary":
                self._transition("primary_search")
                self.process.event.add_timer_handler(
                    self._search_timer, self.search_timeout * 0.5)

    def _promote(self) -> None:
        self.time_started = epoch_now()
        self._transition("primary")
        self.process.transport.set_last_will_and_testament(
            self.boot_topic, self.absent_payload, retain=True)
        if self._on_promote is not None:
            self._on_promote()
        self._announce()

    def _demote(self) -> None:
        self._transition("secondary")
        self.process.transport.clear_last_will_and_testament(
            self.boot_topic)
        if self._on_demote is not None:
            self._on_demote()

    def stop(self) -> None:
        self._stopped = True
        if self.state == "primary":
            # clean handover: clear the retained announcement so the
            # surviving secondary re-elects without waiting on an LWT
            self.process.publish(self.boot_topic, self.absent_payload,
                                 retain=True)
        self.process.remove_message_handler(self._boot_handler,
                                            self.boot_topic)


class Registrar(Actor):
    def __init__(self, process, name: str = "registrar",
                 search_timeout: float = DEFAULT_SEARCH_TIMEOUT):
        super().__init__(process, name,
                         protocol=SERVICE_PROTOCOL_REGISTRAR)
        self.search_timeout = search_timeout
        self.command_aliases["share"] = "share_query"
        self.services_table = Services()
        # control-plane accounting (bench `control_plane` block):
        # registration qps is the registrar's share of the ceiling
        from ..observe.metrics import get_registry
        self._m_adds = get_registry().counter("registrar.adds")
        self._m_removes = get_registry().counter("registrar.removes")
        self.history_ring: deque = deque(maxlen=_HISTORY_RING_SIZE)
        self.share.update({
            "state": "start",
            "service_count": 0,
            "time_started": repr(epoch_now()),
        })

        self._boot_topic = process.topic_path_registrar_boot
        self._state_pattern = f"{process.namespace}/+/+/+/state"
        self.election = RetainedElection(
            process, self._boot_topic, self.topic_path,
            announce=lambda: process.announce_registrar(self.topic_path),
            search_timeout=search_timeout,
            on_promote=self._on_promote, on_demote=self._on_demote,
            on_state=self._on_state)

    # -- election (RetainedElection drives the transitions) ----------------

    @property
    def state(self) -> str:
        return self.election.state

    @property
    def time_started(self) -> float:
        return self.election.time_started

    def _on_state(self, state: str) -> None:
        if self.ec_producer:
            self.ec_producer.update("state", state)
        _LOGGER.debug("%s: state -> %s", self.topic_path, state)

    def _on_promote(self) -> None:
        if self.ec_producer:
            self.ec_producer.update("time_started",
                                    repr(self.time_started))
        self.process.add_message_handler(
            self._service_state_handler, self._state_pattern)

    def _on_demote(self) -> None:
        self.process.remove_message_handler(
            self._service_state_handler, self._state_pattern)
        self.services_table = Services()
        self._update_service_count()

    # -- service table commands (arrive via actor mailbox on /in) ----------

    def add(self, topic_path, name, protocol, transport, owner, tags=None):
        if self.state != "primary":
            return
        fields = ServiceFields(topic_path, name, protocol, transport, owner,
                               tags if isinstance(tags, list) else [tags])
        self.services_table.add_service(fields)
        self.history_ring.append(("add", fields, epoch_now()))
        self._m_adds.inc()
        self._update_service_count()
        self.publish_out("add", fields.to_parameters())

    def remove(self, topic_path):
        if self.state != "primary":
            return
        removed = self.services_table.remove_service(topic_path)
        for fields in removed:
            self.history_ring.append(("remove", fields, epoch_now()))
            self._m_removes.inc()
            self.publish_out("remove", [fields.topic_path])
        if removed:
            self._update_service_count()

    def share_query(self, response_topic, topic_paths="*", name="*",
                    protocol="*", transport="*", owner="*", tags="*"):
        service_filter = ServiceFilter(topic_paths, name, protocol,
                                       transport, owner, tags)
        matches = self.services_table.filter_services(service_filter)
        publish = self.process.publish
        publish(response_topic, generate("item_count", [len(matches)]))
        for fields in matches:
            publish(response_topic, generate("add", fields.to_parameters()))
        publish(response_topic, generate("sync", [self.topic_path]))

    def history(self, response_topic, count="16"):
        count = int(parse_float(count, 16))
        entries = list(self.history_ring)[-count:]
        publish = self.process.publish
        publish(response_topic, generate("item_count", [len(entries)]))
        for command, fields, timestamp in entries:
            publish(response_topic,
                    generate("history",
                             [command, repr(timestamp)]
                             + fields.to_parameters()))

    # -- death reaping -----------------------------------------------------

    def _service_state_handler(self, topic: str, payload: str) -> None:
        try:
            command, _ = parse(payload)
        except ValueError:
            return
        if command != "absent":
            return
        service_topic_path = topic.rsplit("/state", 1)[0]
        self.remove(service_topic_path)

    def _update_service_count(self) -> None:
        # COALESCED share update: a 1,000-service bring-up used to emit
        # ~1,000 per-registration share publishes per lease; stage()
        # folds the storm into one delta per drained mailbox burst
        # (publish count is O(ticks), asserted by tests/test_scale.py)
        if self.ec_producer:
            self.ec_producer.stage(
                "service_count", len(self.services_table))

    def stop(self) -> None:
        was_primary = self.state == "primary"
        self.election.stop()
        if was_primary:
            self.process.remove_message_handler(
                self._service_state_handler, self._state_pattern)
        super().stop()
