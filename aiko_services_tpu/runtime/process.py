# Process runtime: one control-plane endpoint with services, message
# routing, and registrar discovery.
#
# Capability parity with the reference process runtime (reference:
# src/aiko_services/main/process.py:76-350): topic root
# "{namespace}/{hostname}/{process_id}", process liveness via LWT "(absent)"
# on "{root}/0/state", a message-handler table with MQTT wildcard matching,
# every inbound message pumped through the event engine onto the single
# application thread, and the registrar bootstrap handshake over the retained
# topic "{namespace}/service/registrar".
#
# Design departure: Process is instantiable (the reference uses an
# import-time singleton, reference main/__init__.py:72) so N virtual
# processes can share one OS process in hermetic tests -- each gets a unique
# synthetic process_id.  A module-level default process preserves the
# convenient singleton usage.

from __future__ import annotations

import itertools
import os
import threading

from ..transport import create_transport
from ..utils import (
    generate, parse, get_hostname, get_namespace, get_logger, epoch_now)
from ..transport.trie import TopicTrie
from .connection import Connection, ConnectionState
from .event import EventEngine
from .service import ServiceFields

__all__ = ["Process", "default_process", "REGISTRAR_BOOT_VERSION"]

_LOGGER = get_logger("process")
_PROCESS_SEQUENCE = itertools.count()

REGISTRAR_BOOT_VERSION = "2"


class Process:
    def __init__(self, namespace: str = None, transport_kind: str = None,
                 process_id: str = None, transport_kwargs: dict = None):
        self.namespace = namespace or get_namespace()
        self.hostname = get_hostname()
        if process_id is None:
            # unique even when many Processes share one OS process
            sequence = next(_PROCESS_SEQUENCE)
            process_id = (str(os.getpid()) if sequence == 0
                          else f"{os.getpid()}-{sequence}")
        self.process_id = str(process_id)
        self.topic_path_process = (
            f"{self.namespace}/{self.hostname}/{self.process_id}")
        self.topic_path_registrar_boot = (
            f"{self.namespace}/service/registrar")

        self.event = EventEngine(name=f"process-{self.process_id}")
        self.connection = Connection()
        self.registrar: dict | None = None  # {topic_path, version, timestamp}

        self._services: dict[int, object] = {}
        self._service_sequence = itertools.count(1)
        self._message_handlers: dict[str, list] = {}
        # trie-indexed dispatch (transport/trie.py): each inbound
        # message walks the topic's levels once instead of scanning
        # every registered pattern -- the per-message cost that used to
        # grow with every service/stream this process hosts.  The
        # per-pattern sequence number reproduces the historical dict
        # insertion order across handlers of different patterns
        self._handler_trie = TopicTrie()
        self._handler_sequence = itertools.count()
        self._handler_order: dict[str, int] = {}
        self._handlers_lock = threading.Lock()
        self._pending_registrations: list = []

        from ..utils import get_transport_configuration
        self.transport_kind = (
            transport_kind or get_transport_configuration()["kind"])
        self.transport = create_transport(
            self.transport_kind, self._on_transport_message,
            **(transport_kwargs or {}))
        self.event.add_queue_handler(self._message_queue_handler, ["message"])

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Connect the transport and begin registrar discovery; does not
        block (use run() to also own the event loop)."""
        self.transport.set_last_will_and_testament(
            f"{self.topic_path_process}/0/state", "(absent)", retain=True)
        self.connection.update_state(ConnectionState.NETWORK)
        self.transport.connect()
        self.connection.update_state(ConnectionState.TRANSPORT)
        self.add_message_handler(
            self._registrar_boot_handler, self.topic_path_registrar_boot)
        self.publish(f"{self.topic_path_process}/0/state", "(present)",
                     retain=True)

    def run(self, in_thread: bool = False):
        self.start()
        if in_thread:
            return self.event.loop_in_thread()
        self.event.loop()
        return None

    def terminate(self) -> None:
        for service in list(self._services.values()):
            try:
                service.stop()
            except Exception:
                _LOGGER.exception("Service stop failed")
        self.publish(f"{self.topic_path_process}/0/state", "(absent)",
                     retain=True)
        self.transport.disconnect(send_lwt=False)
        self.connection.update_state(ConnectionState.NONE)
        self.event.terminate()

    def crash(self) -> None:
        """Abnormal death for the chaos harness (faults.py
        process_kill / registrar_kill): NO service stop, NO clean
        "(absent)" publish -- the transport severs (every registered
        last-will fires, exactly as a broker reacts to a dead TCP
        session) and the event loop halts mid-flight.  Survivors must
        recover from the LWTs alone: the registrar reaps the services,
        a gateway standby's election fires, journaled streams replay."""
        _LOGGER.warning("%s: injected crash", self.topic_path_process)
        transport = self.transport
        sever = getattr(transport, "sever", None)
        if sever is not None:
            sever()
        else:
            transport.disconnect(send_lwt=True)
        self.connection.update_state(ConnectionState.NONE)
        self.event.terminate()

    def rejoin(self) -> None:
        """After a healed broker partition: reassert liveness (the
        retained "(present)" the partition's LWT overwrote) and
        re-register every service -- the registrar reaped them from
        the "(absent)" notices while we were gone."""
        self.publish(f"{self.topic_path_process}/0/state", "(present)",
                     retain=True)
        if (self.registrar
                and self.connection.is_connected(ConnectionState.REGISTRAR)):
            for service in list(self._services.values()):
                self._register_service(service.service_fields())
        else:
            # no primary in view: the bootstrap handshake re-registers
            # everything when the next "(primary found ...)" arrives
            self._pending_registrations = list(self._services.values())

    # -- services ----------------------------------------------------------

    def add_service(self, service) -> None:
        service.service_id = next(self._service_sequence)
        service.topic_path = (
            f"{self.topic_path_process}/{service.service_id}")
        self._services[service.service_id] = service
        if self.connection.is_connected(ConnectionState.REGISTRAR):
            self._register_service(service.service_fields())
        else:
            self._pending_registrations.append(service)

    def remove_service(self, service) -> None:
        self._services.pop(service.service_id, None)
        if service in self._pending_registrations:
            self._pending_registrations.remove(service)
        if (self.registrar
                and self.connection.is_connected(ConnectionState.TRANSPORT)):
            self.publish(
                f"{self.registrar['topic_path']}/in",
                generate("remove", [service.topic_path]))

    def services(self) -> list:
        return list(self._services.values())

    def _register_service(self, fields: ServiceFields) -> None:
        self.publish(f"{self.registrar['topic_path']}/in",
                     generate("add", fields.to_parameters()))

    # -- messaging ---------------------------------------------------------

    def publish(self, topic: str, payload, retain: bool = False) -> None:
        self.transport.publish(topic, payload, retain)

    def add_message_handler(self, handler, topic: str) -> None:
        with self._handlers_lock:
            first = topic not in self._message_handlers
            self._message_handlers.setdefault(topic, []).append(handler)
            if first:
                self._handler_trie.add(topic, topic)
                self._handler_order[topic] = next(self._handler_sequence)
        if first:
            self.transport.subscribe(topic)

    def remove_message_handler(self, handler, topic: str) -> None:
        last = False
        with self._handlers_lock:
            handlers = self._message_handlers.get(topic, [])
            if handler in handlers:
                handlers.remove(handler)
            if not handlers and topic in self._message_handlers:
                del self._message_handlers[topic]
                self._handler_trie.discard(topic, topic)
                self._handler_order.pop(topic, None)
                last = True
        if last:
            self.transport.unsubscribe(topic)

    def _on_transport_message(self, topic: str, payload: str) -> None:
        # transport dispatch thread -> event-loop thread
        # (reference process.py:247-251)
        self.event.queue_put((topic, payload), "message")

    def _message_queue_handler(self, item) -> None:
        topic, payload = item
        with self._handlers_lock:
            patterns = self._handler_trie.match(topic)
            patterns.sort(key=lambda pattern: self._handler_order.get(
                pattern, 0))
            matched = [handler
                       for pattern in patterns
                       for handler in self._message_handlers.get(
                           pattern, ())]
        for handler in matched:
            try:
                handler(topic, payload)
            except Exception:
                # one failing handler must not starve the others
                import traceback
                _LOGGER.error("Message handler %r failed on %s:\n%s",
                              handler, topic, traceback.format_exc())

    # -- registrar handshake (reference process.py:276-314) ----------------

    def _registrar_boot_handler(self, topic: str, payload: str) -> None:
        try:
            command, parameters = parse(payload)
        except ValueError as error:
            _LOGGER.warning("Bad registrar bootstrap payload dropped: %s",
                            error)
            return
        if command != "primary":
            return
        if parameters and parameters[0] == "found":
            self.registrar = {
                "topic_path": parameters[1],
                "version": parameters[2] if len(parameters) > 2 else "",
                "timestamp": parameters[3] if len(parameters) > 3 else "",
            }
            self.connection.update_state(ConnectionState.REGISTRAR)
            pending, self._pending_registrations = (
                self._pending_registrations, [])
            for service in pending:
                self._register_service(service.service_fields())
        elif parameters and parameters[0] == "absent":
            self.registrar = None
            if self.connection.is_connected(ConnectionState.TRANSPORT):
                self.connection.update_state(ConnectionState.TRANSPORT)
            # services will re-register when a new primary appears
            self._pending_registrations = list(self._services.values())

    def announce_registrar(self, topic_path: str) -> None:
        """Publish the retained registrar-found bootstrap record (called by
        a Registrar service that won the election)."""
        self.publish(
            self.topic_path_registrar_boot,
            generate("primary",
                     ["found", topic_path, REGISTRAR_BOOT_VERSION,
                      repr(epoch_now())]),
            retain=True)


_default_process: Process | None = None
_default_lock = threading.Lock()


def default_process() -> Process:
    global _default_process
    with _default_lock:
        if _default_process is None:
            _default_process = Process()
        return _default_process
