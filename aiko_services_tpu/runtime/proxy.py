# Remote service proxies: call a remote actor's methods as if local.
#
# Capability parity with the reference remote-proxy maker (reference:
# src/aiko_services/main/transport/transport_mqtt.py:109-141): reflect the
# public methods of an interface class and build an object whose every method
# publishes "(method arg ...)" to the target's "{topic_path}/in".

from __future__ import annotations

from ..utils import generate

__all__ = ["get_public_methods", "make_proxy", "RemoteProxy"]


def get_public_methods(interface_class) -> list[str]:
    return sorted(
        name for name in dir(interface_class)
        if not name.startswith("_")
        and callable(getattr(interface_class, name)))


class RemoteProxy:
    """Dynamic proxy: attribute access returns a publisher for any method
    name; an optional interface class restricts the surface."""

    def __init__(self, process, topic_in: str, interface_class=None):
        object.__setattr__(self, "_process", process)
        object.__setattr__(self, "_topic_in", topic_in)
        methods = (set(get_public_methods(interface_class))
                   if interface_class is not None else None)
        object.__setattr__(self, "_methods", methods)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        if self._methods is not None and name not in self._methods:
            raise AttributeError(
                f"{name} is not part of the proxied interface")

        def remote_call(*args):
            self._process.publish(self._topic_in, generate(name, args))

        remote_call.__name__ = name
        return remote_call

    def __repr__(self):
        return f"RemoteProxy({self._topic_in})"


def make_proxy(process, topic_path: str, interface_class=None) -> RemoteProxy:
    """topic_path may be the service root or the /in topic itself."""
    topic_in = (topic_path if topic_path.endswith("/in")
                else f"{topic_path}/in")
    return RemoteProxy(process, topic_in, interface_class)
