# Remote service proxies + AOP method tracing.
#
# Capability parity with the reference remote-proxy maker (reference:
# src/aiko_services/main/transport/transport_mqtt.py:109-141): reflect the
# public methods of an interface class and build an object whose every method
# publishes "(method arg ...)" to the target's "{topic_path}/in" -- and with
# the reference's ProxyAllMethods/proxy_trace AOP wrapper (reference:
# src/aiko_services/main/proxy.py:39-72), here without the wrapt dependency.

from __future__ import annotations

import time

from ..utils import generate, get_logger

__all__ = ["get_public_methods", "make_proxy", "RemoteProxy",
           "TracingProxy", "trace_all_methods", "log_trace"]

_LOGGER = get_logger("proxy")


def get_public_methods(interface_class) -> list[str]:
    return sorted(
        name for name in dir(interface_class)
        if not name.startswith("_")
        and callable(getattr(interface_class, name)))


class RemoteProxy:
    """Dynamic proxy: attribute access returns a publisher for any method
    name; an optional interface class restricts the surface."""

    def __init__(self, process, topic_in: str, interface_class=None):
        object.__setattr__(self, "_process", process)
        object.__setattr__(self, "_topic_in", topic_in)
        methods = (set(get_public_methods(interface_class))
                   if interface_class is not None else None)
        object.__setattr__(self, "_methods", methods)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        if self._methods is not None and name not in self._methods:
            raise AttributeError(
                f"{name} is not part of the proxied interface")

        def remote_call(*args):
            self._process.publish(self._topic_in, generate(name, args))

        remote_call.__name__ = name
        return remote_call

    def __repr__(self):
        return f"RemoteProxy({self._topic_in})"


def make_proxy(process, topic_path: str, interface_class=None) -> RemoteProxy:
    """topic_path may be the service root or the /in topic itself."""
    topic_in = (topic_path if topic_path.endswith("/in")
                else f"{topic_path}/in")
    return RemoteProxy(process, topic_in, interface_class)


def log_trace(name: str, phase: str, elapsed: float | None,
              args: tuple, result) -> None:
    """Default tracer: enter/exit lines with wall time (the reference's
    proxy_trace printer, proxy.py:64-72)."""
    if phase == "enter":
        _LOGGER.info("TRACE > %s%r", name, args)
    elif phase == "error":
        _LOGGER.info("TRACE ! %s raised %r (%.3f ms)", name, result,
                     (elapsed or 0.0) * 1e3)
    else:
        _LOGGER.info("TRACE < %s -> %r (%.3f ms)", name, result,
                     (elapsed or 0.0) * 1e3)


class TracingProxy:
    """AOP wrapper: every public method call on the wrapped object passes
    through `tracer(name, phase, elapsed, args, result)` -- the
    reference's ProxyAllMethods capability (proxy.py:39-62) built on
    plain __getattr__ delegation instead of wrapt.  Non-callable and
    underscore attributes pass through untraced.  LIMITATION: special-
    method protocol lookups (`with`, `len()`, iteration, calling the
    proxy itself) resolve on the proxy TYPE and bypass __getattr__ --
    wrap objects whose API is named methods, not protocol objects."""

    def __init__(self, target, tracer=None):
        object.__setattr__(self, "_target", target)
        object.__setattr__(self, "_tracer", tracer or log_trace)
        object.__setattr__(self, "_traced_cache", {})

    def __getattr__(self, name):
        value = getattr(self._target, name)
        if name.startswith("_") or not callable(value):
            return value
        cached = self._traced_cache.get(name)
        if cached is not None and cached.__wrapped__ == value:
            return cached  # stable identity: proxy.m is proxy.m
        tracer = self._tracer

        def traced(*args, **kwargs):
            tracer(name, "enter", None, args, None)
            start = time.perf_counter()
            try:
                result = value(*args, **kwargs)
            except BaseException as error:
                tracer(name, "error", time.perf_counter() - start, args,
                       error)
                raise
            tracer(name, "exit", time.perf_counter() - start, args,
                   result)
            return result

        traced.__name__ = name
        traced.__wrapped__ = value
        self._traced_cache[name] = traced
        return traced

    def __setattr__(self, name, value):
        setattr(self._target, name, value)

    def __repr__(self):
        return f"TracingProxy({self._target!r})"


def trace_all_methods(target, tracer=None) -> TracingProxy:
    return TracingProxy(target, tracer)
