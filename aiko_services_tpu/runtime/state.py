# Finite state machine.
#
# Capability parity with the reference StateMachine (reference:
# src/aiko_services/main/state.py:21-61, a thin wrapper over the
# third-party `transitions` library whose transition() failure raises
# SystemExit).  Self-contained here: declared states + named transitions
# with on-enter callbacks on a model object; invalid transitions raise
# StateMachineError (not SystemExit -- callers decide severity).

from __future__ import annotations

from ..utils import get_logger

__all__ = ["StateMachine", "StateMachineError"]

_LOGGER = get_logger("state")


class StateMachineError(Exception):
    pass


class StateMachine:
    """transitions: [{"name": ..., "source": str | list | "*",
    "dest": ...}]; on entering state S, model.on_enter_S() fires if
    defined (matching the `transitions` library convention the reference
    relies on, registrar.py:139-188)."""

    def __init__(self, model, states: list, transitions: list,
                 initial: str):
        self.model = model
        self.states = list(states)
        self.state = initial
        self._transitions: dict[str, list] = {}
        for record in transitions:
            self._transitions.setdefault(record["name"], []).append(record)
        if initial not in self.states:
            raise StateMachineError(f"Unknown initial state: {initial}")

    def transition(self, name: str, **kwargs) -> None:
        records = self._transitions.get(name)
        if not records:
            raise StateMachineError(f"Unknown transition: {name}")
        for record in records:
            source = record["source"]
            sources = ([source] if isinstance(source, str) else
                       list(source))
            if "*" in sources or self.state in sources:
                destination = record["dest"]
                if destination not in self.states:
                    raise StateMachineError(
                        f"Unknown destination state: {destination}")
                previous = self.state
                self.state = destination
                _LOGGER.debug("%s: %s: %s -> %s",
                              type(self.model).__name__, name, previous,
                              destination)
                handler = getattr(self.model,
                                  f"on_enter_{destination}", None)
                if handler is not None:
                    handler(**kwargs)
                return
        raise StateMachineError(
            f"Transition '{name}' invalid from state '{self.state}'")

    def get_state(self) -> str:
        return self.state
