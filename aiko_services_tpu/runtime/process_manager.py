# ProcessManager: spawn and reap OS child processes.
#
# Capability parity with the reference ProcessManager (reference:
# src/aiko_services/main/process_manager.py:48-110): Popen children keyed
# by id, bare module names resolved to file paths via importlib, a
# background poll thread reaping exits into a process_exit_handler.

from __future__ import annotations

import importlib.util
import subprocess
import sys
import threading

from ..utils import get_logger

__all__ = ["ProcessManager"]

_LOGGER = get_logger("process_manager")
_POLL_INTERVAL = 0.2  # reference process_manager.py poll cadence


class ProcessManager:
    def __init__(self, process_exit_handler=None):
        self.process_exit_handler = process_exit_handler
        self.processes: dict = {}   # id -> {"process": Popen, "command":..}
        self._escalating: list = []  # children awaiting SIGKILL escalation
        self._lock = threading.Lock()
        self._monitor: threading.Thread | None = None
        self._terminated = False

    @staticmethod
    def resolve_command(command: str) -> str:
        """Bare module name -> source file path (reference
        process_manager.py:63-80); paths and executables pass through."""
        if "/" in command or command.endswith(".py"):
            return command
        specification = importlib.util.find_spec(command)
        if specification is not None and specification.origin:
            return specification.origin
        return command

    def spawn(self, process_id, command: str, arguments=(),
              use_interpreter: bool = True,
              start_new_session: bool = False,
              stdout=None, stderr=None, env=None):
        """`start_new_session` detaches the child from the caller's
        terminal session (its own setsid), so closing the terminal
        does not SIGHUP it -- what `aiko system start` needs for a
        deployment that outlives the shell.  Detached children should
        also get their own `stdout`/`stderr` (a log file): inheriting
        the caller's keeps any pipe on it open forever.

        `env` is an OVERLAY merged over the parent's os.environ, not a
        replacement: autoscaled replica children must inherit the
        ambient environment (PATH, PYTHONPATH, proxy settings) plus the
        handful of knobs the spawner pins -- JAX_PLATFORMS, the
        persistent compile-cache directory, telemetry switches.  A None
        value in the overlay REMOVES that variable from the child."""
        import os
        command_path = self.resolve_command(command)
        argv = ([sys.executable, command_path] if use_interpreter
                else [command_path])
        argv += [str(argument) for argument in arguments]
        merged_env = None
        if env:
            merged_env = dict(os.environ)
            for key, value in env.items():
                if value is None:
                    merged_env.pop(str(key), None)
                else:
                    merged_env[str(key)] = str(value)
        child = subprocess.Popen(argv,
                                 start_new_session=start_new_session,
                                 stdout=stdout, stderr=stderr,
                                 env=merged_env)
        with self._lock:
            self.processes[process_id] = {
                "process": child, "command": command_path}
            if self._monitor is None:
                self._monitor = threading.Thread(
                    target=self._monitor_loop, name="process-manager",
                    daemon=True)
                self._monitor.start()
        _LOGGER.info("Spawned %s: pid %d: %s",
                     process_id, child.pid, " ".join(argv))
        return child

    def kill(self, process_id, timeout: float = 5.0) -> None:
        """Synchronously pop the record and send SIGTERM, so membership
        reflects the kill the moment this returns; the grace wait and
        SIGKILL escalation happen off-thread so callers (e.g. the event
        loop) never block on a stubborn child.  The pop and the
        _escalating registration share one lock acquisition so a
        concurrent terminate() can never miss the child."""
        with self._lock:
            record = self.processes.pop(process_id, None)
            if record is None:
                return
            child = record["process"]
            self._escalating.append(child)
        child.terminate()
        threading.Thread(target=self._reap, args=(child, timeout),
                         name=f"process-manager-kill-{process_id}",
                         daemon=True).start()

    def _reap(self, child, timeout: float) -> None:
        try:
            child.wait(timeout)
        except subprocess.TimeoutExpired:
            child.kill()
            child.wait()
        finally:
            with self._lock:
                if child in self._escalating:
                    self._escalating.remove(child)

    def kill_all(self) -> None:
        for process_id in list(self.processes):
            self.kill(process_id)

    def __contains__(self, process_id) -> bool:
        return process_id in self.processes

    def _monitor_loop(self) -> None:
        import time
        from ..faults import get_injector
        while not self._terminated:
            injector = get_injector()
            if injector is not None:
                # seeded chaos (faults.py process_kill): one consult
                # per poll per child -- frame=k kills that child on
                # its k-th poll, deterministically.  Disabled (the
                # production state) this is one is-None check per poll
                with self._lock:
                    records = list(self.processes.items())
                for process_id, record in records:
                    if injector.process_kill(process_id):
                        _LOGGER.warning(
                            "Injected process_kill fired on %s",
                            process_id)
                        # SIGKILL without popping the record: the child
                        # dies ABNORMALLY and the reap below observes
                        # the exit, so process_exit_handler fires
                        # exactly as for a real crash (kill() is the
                        # deliberate-retirement path and suppresses it)
                        try:
                            record["process"].kill()
                        except OSError:
                            pass
            exited = []
            with self._lock:
                for process_id, record in list(self.processes.items()):
                    return_code = record["process"].poll()
                    if return_code is not None:
                        exited.append((process_id, record, return_code))
                        del self.processes[process_id]
            for process_id, record, return_code in exited:
                _LOGGER.info("Process %s exited: %d",
                             process_id, return_code)
                if self.process_exit_handler:
                    try:
                        self.process_exit_handler(process_id, return_code)
                    except Exception:
                        _LOGGER.exception("process_exit_handler failed")
            time.sleep(_POLL_INTERVAL)

    def terminate(self, grace: float = 5.0) -> None:
        """Shutdown path must not rely on daemon escalation threads (they
        die with the interpreter): all children get SIGTERM concurrently,
        then ONE shared grace window to exit cleanly, then SIGKILL for
        stragglers -- so no SIGTERM-ignoring child survives as an orphan
        and shutdown is bounded by `grace`, not grace-per-child."""
        import time
        self._terminated = True    # stops the monitor loop
        self.kill_all()            # concurrent SIGTERM + async reaps
        deadline = time.monotonic() + grace
        while time.monotonic() < deadline:
            with self._lock:
                if not self._escalating:
                    return
            time.sleep(0.02)
        with self._lock:
            stragglers = list(self._escalating)
            self._escalating.clear()
        for child in stragglers:
            if child.poll() is None:
                child.kill()
            child.wait()
