# Actor layer: deferred method invocation over mailboxes.
#
# Capability parity with the reference actor layer (reference:
# src/aiko_services/main/actor.py:107-283): inbound S-expressions on
# "{topic_path}/in" parse into Message(command, parameters) records posted to
# per-actor mailboxes; the control mailbox is registered first so control
# traffic preempts data traffic (reference actor.py:208-213); messages invoke
# actual methods on the event-loop thread; invalid commands are logged, not
# fatal.  Local calls can be deferred through post_message, and timed
# delivery uses the event engine's timers.

from __future__ import annotations

from ..utils import parse, generate, get_logger
from .service import Service

__all__ = ["Actor", "ActorMessage", "ActorTopic"]

_LOGGER = get_logger("actor")


class ActorTopic:
    CONTROL = "control"
    IN = "in"
    OUT = "out"
    STATE = "state"


class ActorMessage:
    """One deferred method call (reference actor.py:122-159)."""

    __slots__ = ("target", "command", "parameters")

    def __init__(self, target, command: str, parameters):
        self.target = target
        self.command = command
        self.parameters = parameters

    def invoke(self) -> None:
        aliases = getattr(self.target, "command_aliases", None)
        command = (aliases.get(self.command, self.command)
                   if aliases else self.command)
        method = getattr(self.target, command, None)
        if method is None or not callable(method):
            _LOGGER.warning(
                "%s: unknown command: %s",
                getattr(self.target, "name", self.target), self.command)
            return
        try:
            method(*self.parameters)
        except TypeError as error:
            _LOGGER.error(
                "%s: bad arguments for %s%r: %s",
                getattr(self.target, "name", self.target),
                self.command, tuple(self.parameters), error)

    def __repr__(self):
        return f"ActorMessage({self.command}{tuple(self.parameters)!r})"


class Actor(Service):
    def __init__(self, process, name: str, protocol: str = None,
                 tags=None, owner: str = ""):
        super().__init__(process, name, protocol=protocol, tags=tags,
                         owner=owner)
        import logging
        self.share: dict = {
            "lifecycle": "ready",
            "name": name,
            "protocol": self.protocol,
            "tags": self.tags,
            "log_level": logging.getLevelName(
                self.logger.getEffectiveLevel()),
        }
        self.ec_producer = None
        # wire-command -> method-name aliases (lets a command like "share"
        # coexist with the share dict attribute)
        self.command_aliases: dict[str, str] = {}

        # control mailbox first: priority over in (reference actor.py:208)
        self._mailbox_control = f"{self.topic_path}/#control"
        self._mailbox_in = f"{self.topic_path}/#in"
        engine = process.event
        engine.add_mailbox_handler(self._mailbox_handler,
                                   self._mailbox_control)
        engine.add_mailbox_handler(self._mailbox_handler, self._mailbox_in)
        self.add_message_handler(self._topic_in_handler, self.topic_in)
        self.add_message_handler(self._topic_control_handler,
                                 self.topic_control)
        # every actor shares its state over EC (reference actor.py:199-205)
        from .share import ECProducer
        ECProducer(self)

    # -- inbound message routing ------------------------------------------

    def _topic_in_handler(self, topic: str, payload: str) -> None:
        try:
            command, parameters = parse(payload)
        except ValueError as error:
            _LOGGER.warning("%s: unparseable payload dropped: %s",
                            self.name, error)
            return
        if command:
            self._post_message(ActorTopic.IN, command, parameters)

    def _topic_control_handler(self, topic: str, payload: str) -> None:
        try:
            command, parameters = parse(payload)
        except ValueError as error:
            _LOGGER.warning("%s: unparseable control payload dropped: %s",
                            self.name, error)
            return
        if not command:
            return
        if self.ec_producer is not None and self.ec_producer.handles(command):
            self.ec_producer.handle(command, parameters)
            return
        self._post_message(ActorTopic.CONTROL, command, parameters)

    def _post_message(self, actor_topic: str, command: str,
                      parameters) -> None:
        # "control_" prefixed commands always ride the control mailbox
        # (reference actor.py:183-192)
        if command.startswith("control_"):
            actor_topic = ActorTopic.CONTROL
        mailbox = (self._mailbox_control
                   if actor_topic == ActorTopic.CONTROL
                   else self._mailbox_in)
        self.process.event.mailbox_put(
            mailbox, ActorMessage(self, command, parameters))

    def _mailbox_handler(self, mailbox_name: str, message) -> None:
        message.invoke()

    def _ec_flush_staged(self) -> None:
        """Mailbox continuation of ECProducer.stage(): the flush
        message queues behind the churn burst that staged the updates,
        so one delta publish covers the whole drained burst."""
        if self.ec_producer is not None:
            self.ec_producer.flush_staged()

    def _ec_change_hook(self, command: str, name: str, value) -> None:
        """Live log_level updates via the share dict, e.g. dashboard
        publishing "(update log_level DEBUG)" to /control (reference
        actor.py:259-265)."""
        if command == "update" and name == "log_level":
            try:
                self.logger.setLevel(str(value).upper())
            except ValueError:
                _LOGGER.warning("%s: bad log_level ignored: %r",
                                self.name, value)

    # -- local API ---------------------------------------------------------

    def post_message(self, command: str, parameters=(),
                     actor_topic: str = ActorTopic.IN) -> None:
        """Defer a local method call through the mailbox (preserves actor
        ordering semantics for self-sends)."""
        self._post_message(actor_topic, command, list(parameters))

    def post_message_later(self, command: str, parameters=(),
                           delay: float = 0.0) -> None:
        engine = self.process.event

        def fire():
            engine.remove_timer_handler(fire)
            self.post_message(command, parameters)

        engine.add_timer_handler(fire, delay)

    def publish_out(self, command: str, parameters=()) -> None:
        self.process.publish(self.topic_out, generate(command, parameters))

    def terminate(self) -> None:
        """Wire-invocable kill: "(terminate)" on /in tears down the whole
        process (reference dashboard kill, dashboard.py:368-377)."""
        self.process.terminate()

    def stop(self) -> None:
        engine = self.process.event
        engine.remove_mailbox_handler(self._mailbox_control)
        engine.remove_mailbox_handler(self._mailbox_in)
        self.remove_message_handler(self._topic_in_handler, self.topic_in)
        self.remove_message_handler(self._topic_control_handler,
                                    self.topic_control)
        super().stop()
