# Connection-state ladder (capability parity with reference
# src/aiko_services/main/connection.py:12-46):
# NONE < NETWORK < TRANSPORT < REGISTRAR.  Handlers fire on every transition;
# is_connected(state) means "at least state".

from __future__ import annotations

from enum import IntEnum

__all__ = ["ConnectionState", "Connection"]


class ConnectionState(IntEnum):
    NONE = 0
    NETWORK = 1
    TRANSPORT = 2
    REGISTRAR = 3


class Connection:
    def __init__(self):
        self._state = ConnectionState.NONE
        self._handlers: list = []

    @property
    def state(self) -> ConnectionState:
        return self._state

    def add_handler(self, handler) -> None:
        self._handlers.append(handler)
        handler(self, self._state)  # immediately report current state

    def remove_handler(self, handler) -> None:
        if handler in self._handlers:
            self._handlers.remove(handler)

    def is_connected(self, state: ConnectionState) -> bool:
        return self._state >= state

    def update_state(self, state: ConnectionState) -> None:
        if state == self._state:
            return
        self._state = state
        for handler in list(self._handlers):
            handler(self, state)
