# Storage service: sqlite-backed key-value actor + the request/response
# idioms.
#
# Capability parity with the reference storage layer (reference:
# src/aiko_services/main/storage.py:49-103): a sqlite actor and the two
# generic invocation idioms -- do_command (discover a service by filter,
# proxy, invoke) and do_request (command + paged "(item_count N)" response
# collection on a dedicated response topic).

from __future__ import annotations

import json
import sqlite3

from ..utils import generate, get_logger, parse, parse_number
from .actor import Actor
from .proxy import make_proxy
from .service import ServiceFilter
from .share import ServicesCache, services_cache_create_singleton

__all__ = ["Storage", "do_command", "do_request"]

_LOGGER = get_logger("storage")
SERVICE_PROTOCOL_STORAGE = "storage:0"


class Storage(Actor):
    """Key-value store over sqlite.  Commands on /in:
    (save key value) | (load key response_topic) | (delete key) |
    (keys response_topic)."""

    def __init__(self, process, name: str = "storage",
                 database_path: str = ":memory:"):
        super().__init__(process, name, protocol=SERVICE_PROTOCOL_STORAGE)
        self._connection = sqlite3.connect(
            database_path, check_same_thread=False)
        self._connection.execute(
            "CREATE TABLE IF NOT EXISTS store "
            "(key TEXT PRIMARY KEY, value TEXT)")
        self._connection.commit()

    def save(self, key, value) -> None:
        self._connection.execute(
            "INSERT OR REPLACE INTO store (key, value) VALUES (?, ?)",
            (str(key), json.dumps(value)))
        self._connection.commit()

    def load(self, key, response_topic) -> None:
        row = self._connection.execute(
            "SELECT value FROM store WHERE key = ?",
            (str(key),)).fetchone()
        items = [] if row is None else [row[0]]  # stored JSON text
        self._respond(response_topic, items)

    def delete(self, key) -> None:
        self._connection.execute(
            "DELETE FROM store WHERE key = ?", (str(key),))
        self._connection.commit()

    def keys(self, response_topic) -> None:
        rows = self._connection.execute(
            "SELECT key FROM store ORDER BY key").fetchall()
        self._respond(response_topic, [row[0] for row in rows])

    def _respond(self, response_topic, items) -> None:
        """items are wire-ready strings (keys, or stored JSON text)."""
        publish = self.process.publish
        publish(response_topic, generate("item_count", [len(items)]))
        for item in items:
            publish(response_topic, generate("item", [item]))

    def stop(self) -> None:
        self._connection.close()
        super().stop()


def do_command(process, service_filter: ServiceFilter, command_handler,
               services_cache: ServicesCache | None = None):
    """Discover the first service matching the filter, then invoke
    command_handler(proxy) (reference storage.py:67-81).  Returns the
    ServicesCache handler so callers may detach it."""
    cache = services_cache or services_cache_create_singleton(process)
    invoked = []

    def on_service(command, fields):
        if command == "add" and not invoked:
            invoked.append(fields)
            cache.remove_handler(on_service)  # one-shot
            command_handler(make_proxy(process, fields.topic_path))

    cache.add_handler(on_service, service_filter)
    return on_service


def do_request(process, service_filter: ServiceFilter, request_handler,
               response_handler, item_types=("item",),
               services_cache: ServicesCache | None = None) -> str:
    """do_command + paged response collection (reference storage.py:87-103):
    request_handler(proxy, response_topic) issues the command; responses
    arrive as "(item_count N)" then N item payloads; response_handler(items)
    fires once all pages arrive.  Returns the response topic."""
    import itertools
    sequence = getattr(do_request, "_sequence", None)
    if sequence is None:
        sequence = do_request._sequence = itertools.count()
    response_topic = (f"{process.topic_path_process}/0/request/"
                      f"{next(sequence)}")
    collected = []
    expected = [None]

    def on_response(topic, payload):
        command, parameters = parse(payload)
        if command == "item_count" and parameters:
            expected[0] = int(parse_number(parameters[0], 0))
        elif command in item_types:
            collected.append(parameters[0] if len(parameters) == 1
                             else list(parameters))
        if expected[0] is not None and len(collected) >= expected[0]:
            process.remove_message_handler(on_response, response_topic)
            response_handler(collected)

    process.add_message_handler(on_response, response_topic)
    do_command(process, service_filter,
               lambda proxy: request_handler(proxy, response_topic),
               services_cache=services_cache)
    return response_topic
