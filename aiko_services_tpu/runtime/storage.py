# Storage service: sqlite-backed key-value actor + the request/response
# idioms.
#
# Capability parity with the reference storage layer (reference:
# src/aiko_services/main/storage.py:49-103): a sqlite actor and the two
# generic invocation idioms -- do_command (discover a service by filter,
# proxy, invoke) and do_request (command + paged "(item_count N)" response
# collection on a dedicated response topic).
#
# The sqlite KV core is split out as KeyValueStore so non-actor layers
# (the serving gateway's crash journal, serve/journal.py) persist
# through the SAME backend without paying the wire: one schema, one
# durability story, whether keys arrive over `/in` or from the gateway
# tick.

from __future__ import annotations

import json
import sqlite3
import threading

from ..utils import generate, get_logger, parse, parse_number
from .actor import Actor
from .proxy import make_proxy
from .service import ServiceFilter
from .share import ServicesCache, services_cache_create_singleton

__all__ = ["KeyValueStore", "Storage", "do_command", "do_request"]

_LOGGER = get_logger("storage")
SERVICE_PROTOCOL_STORAGE = "storage:0"


class KeyValueStore:
    """The sqlite key-value core shared by the Storage actor and the
    gateway journal: JSON values under TEXT keys, with a batched
    write path (`write_batch`: one transaction per journal tick, not
    one commit per key) and prefix scans for replay."""

    def __init__(self, database_path: str = ":memory:"):
        self.database_path = database_path
        self._connection = sqlite3.connect(
            database_path, check_same_thread=False)
        self._lock = threading.Lock()
        self._connection.execute(
            "CREATE TABLE IF NOT EXISTS store "
            "(key TEXT PRIMARY KEY, value TEXT)")
        self._connection.commit()

    def save(self, key, value) -> None:
        with self._lock:
            self._connection.execute(
                "INSERT OR REPLACE INTO store (key, value) VALUES (?, ?)",
                (str(key), json.dumps(value)))
            self._connection.commit()

    def write_batch(self, items: dict, deletes=()) -> None:
        """Upserts + deletes in ONE transaction: a failure mid-batch
        rolls back, so the store never holds a half-applied tick (an
        unencodable value must not leave its batch-siblings pending on
        the shared connection for the NEXT commit to sweep in)."""
        with self._lock:
            try:
                for key, value in items.items():
                    self._connection.execute(
                        "INSERT OR REPLACE INTO store (key, value) "
                        "VALUES (?, ?)", (str(key), json.dumps(value)))
                for key in deletes:
                    self._connection.execute(
                        "DELETE FROM store WHERE key = ?", (str(key),))
                self._connection.commit()
            except Exception:
                self._connection.rollback()
                raise

    def count(self, prefix: str = "") -> int:
        with self._lock:
            if prefix:
                row = self._connection.execute(
                    "SELECT COUNT(*) FROM store WHERE key LIKE ?",
                    (prefix + "%",)).fetchone()
            else:
                row = self._connection.execute(
                    "SELECT COUNT(*) FROM store").fetchone()
        return int(row[0])

    def load(self, key):
        """Decoded value, or None when absent."""
        with self._lock:
            row = self._connection.execute(
                "SELECT value FROM store WHERE key = ?",
                (str(key),)).fetchone()
        return None if row is None else json.loads(row[0])

    def load_text(self, key) -> str | None:
        """Stored JSON text (the Storage actor's wire unit)."""
        with self._lock:
            row = self._connection.execute(
                "SELECT value FROM store WHERE key = ?",
                (str(key),)).fetchone()
        return None if row is None else row[0]

    def delete(self, key) -> None:
        with self._lock:
            self._connection.execute(
                "DELETE FROM store WHERE key = ?", (str(key),))
            self._connection.commit()

    def keys(self, prefix: str = "") -> list:
        with self._lock:
            if prefix:
                rows = self._connection.execute(
                    "SELECT key FROM store WHERE key LIKE ? ORDER BY key",
                    (prefix + "%",)).fetchall()
            else:
                rows = self._connection.execute(
                    "SELECT key FROM store ORDER BY key").fetchall()
        return [row[0] for row in rows]

    def items(self, prefix: str = "") -> list:
        """[(key, decoded value)] sorted by key."""
        with self._lock:
            if prefix:
                rows = self._connection.execute(
                    "SELECT key, value FROM store WHERE key LIKE ? "
                    "ORDER BY key", (prefix + "%",)).fetchall()
            else:
                rows = self._connection.execute(
                    "SELECT key, value FROM store ORDER BY key").fetchall()
        return [(key, json.loads(value)) for key, value in rows]

    def close(self) -> None:
        with self._lock:
            self._connection.close()


class Storage(Actor):
    """Key-value store over sqlite.  Commands on /in:
    (save key value) | (load key response_topic) | (delete key) |
    (keys response_topic)."""

    def __init__(self, process, name: str = "storage",
                 database_path: str = ":memory:"):
        super().__init__(process, name, protocol=SERVICE_PROTOCOL_STORAGE)
        self.store = KeyValueStore(database_path)

    def save(self, key, value) -> None:
        self.store.save(key, value)

    def load(self, key, response_topic) -> None:
        text = self.store.load_text(key)
        items = [] if text is None else [text]  # stored JSON text
        self._respond(response_topic, items)

    def delete(self, key) -> None:
        self.store.delete(key)

    def keys(self, response_topic) -> None:
        self._respond(response_topic, self.store.keys())

    def _respond(self, response_topic, items) -> None:
        """items are wire-ready strings (keys, or stored JSON text)."""
        publish = self.process.publish
        publish(response_topic, generate("item_count", [len(items)]))
        for item in items:
            publish(response_topic, generate("item", [item]))

    def stop(self) -> None:
        self.store.close()
        super().stop()


def do_command(process, service_filter: ServiceFilter, command_handler,
               services_cache: ServicesCache | None = None):
    """Discover the first service matching the filter, then invoke
    command_handler(proxy) (reference storage.py:67-81).  Returns the
    ServicesCache handler so callers may detach it."""
    cache = services_cache or services_cache_create_singleton(process)
    invoked = []

    def on_service(command, fields):
        if command == "add" and not invoked:
            invoked.append(fields)
            cache.remove_handler(on_service)  # one-shot
            command_handler(make_proxy(process, fields.topic_path))

    cache.add_handler(on_service, service_filter)
    return on_service


def do_request(process, service_filter: ServiceFilter, request_handler,
               response_handler, item_types=("item",),
               services_cache: ServicesCache | None = None) -> str:
    """do_command + paged response collection (reference storage.py:87-103):
    request_handler(proxy, response_topic) issues the command; responses
    arrive as "(item_count N)" then N item payloads; response_handler(items)
    fires once all pages arrive.  Returns the response topic."""
    import itertools
    sequence = getattr(do_request, "_sequence", None)
    if sequence is None:
        sequence = do_request._sequence = itertools.count()
    response_topic = (f"{process.topic_path_process}/0/request/"
                      f"{next(sequence)}")
    collected = []
    expected = [None]

    def on_response(topic, payload):
        command, parameters = parse(payload)
        if command == "item_count" and parameters:
            expected[0] = int(parse_number(parameters[0], 0))
        elif command in item_types:
            collected.append(parameters[0] if len(parameters) == 1
                             else list(parameters))
        if expected[0] is not None and len(collected) >= expected[0]:
            process.remove_message_handler(on_response, response_topic)
            response_handler(collected)

    process.add_message_handler(on_response, response_topic)
    do_command(process, service_filter,
               lambda proxy: request_handler(proxy, response_topic),
               services_cache=services_cache)
    return response_topic
