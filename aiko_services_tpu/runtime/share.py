# Eventually-consistent state sharing.
#
# Capability parity with the reference EC layer (reference:
# src/aiko_services/main/share.py:153-656): an ECProducer exposes a
# service's "share" dictionary over its control topic with commands
# (add/update/remove name value) and leased "(share response_topic
# lease_time filter)" subscriptions; full sync is "(item_count N)" + N x
# "(add name value)" + "(sync ...)"; an ECConsumer mirrors a remote share
# into a local dict with an auto-extending lease; ServicesCache mirrors the
# registrar's service table and notifies filtered handlers on changes.
#
# Share keys may be dotted "a.b" for one level of nesting (reference
# share.py:115-119 allows <= 2 levels).

from __future__ import annotations

import itertools

from ..utils import generate, parse_number, get_logger
from .connection import ConnectionState
from .lease import Lease
from .service import ServiceFields, ServiceFilter, Services

__all__ = ["ECProducer", "ECConsumer", "ServicesCache",
           "services_cache_create_singleton"]

_LOGGER = get_logger("share")
_EC_COMMANDS = frozenset(("add", "update", "remove", "share"))
DEFAULT_LEASE_TIME = 300.0  # seconds (reference share.py:86)


_SHARE_COUNTERS = None


def _share_counters():
    """(publishes, delta_publishes, updates_coalesced) resolved ONCE
    from the process-global registry -- stage() rides stream-churn
    storms, so the per-update cost must stay a plain int add (the
    counters feed the bench `control_plane` block)."""
    global _SHARE_COUNTERS
    if _SHARE_COUNTERS is None:
        from ..observe.metrics import get_registry
        registry = get_registry()
        _SHARE_COUNTERS = (registry.counter("share.publishes"),
                           registry.counter("share.delta_publishes"),
                           registry.counter("share.updates_coalesced"))
    return _SHARE_COUNTERS


def _get_nested(share: dict, name: str):
    if "." in name:
        head, tail = name.split(".", 1)
        value = share.get(head)
        if isinstance(value, dict):
            return value.get(tail)
        return None
    return share.get(name)


def _set_nested(share: dict, name: str, value) -> None:
    if "." in name:
        head, tail = name.split(".", 1)
        share.setdefault(head, {})[tail] = value
    else:
        share[name] = value


def _remove_nested(share: dict, name: str) -> None:
    if "." in name:
        head, tail = name.split(".", 1)
        if isinstance(share.get(head), dict):
            share[head].pop(tail, None)
    else:
        share.pop(name, None)


def _flatten(share: dict) -> list[tuple[str, object]]:
    items = []
    for key, value in share.items():
        if isinstance(value, dict):
            for sub_key, sub_value in value.items():
                items.append((f"{key}.{sub_key}", sub_value))
        else:
            items.append((key, value))
    return items


class ECProducer:
    def __init__(self, service, share: dict = None):
        self.service = service
        self.share = share if share is not None else getattr(
            service, "share", {})
        self._leases: dict[str, Lease] = {}  # response_topic -> Lease
        self._change_handlers: list = []
        # coalesced publishing (stage/flush_staged): a burst of staged
        # updates within one event-loop tick folds into ONE `(delta
        # {...})` payload per lease -- the control-plane publish count
        # becomes O(ticks), not O(updates).  `_last_flushed` shadows
        # published SCALAR values so an unchanged re-stage publishes
        # nothing at all
        self._staged: dict = {}
        self._forced: set = set()
        self._last_flushed: dict = {}
        self._flush_scheduled = False
        # every Actor auto-creates a producer (reference actor.py:199-205);
        # an explicit later ECProducer(service) replaces it cleanly
        previous = getattr(service, "ec_producer", None)
        if previous is not None:
            previous.terminate()
        service.ec_producer = self
        service.add_tags(["ec=true"])
        # services opt into change notifications (e.g. Actor's live
        # log_level hook) by defining _ec_change_hook
        hook = getattr(service, "_ec_change_hook", None)
        if hook is not None:
            self.add_change_handler(hook)

    def handles(self, command: str) -> bool:
        return command in _EC_COMMANDS

    def add_change_handler(self, handler) -> None:
        """handler(command, name, value) on every local or remote change."""
        self._change_handlers.append(handler)

    # -- remote commands arriving on the control topic ---------------------

    def handle(self, command: str, parameters) -> None:
        if command == "share":
            self._handle_share(parameters)
        elif command in ("add", "update") and len(parameters) >= 2:
            self.update(parameters[0], parameters[1])
        elif command == "remove" and parameters:
            self.remove(parameters[0])

    def _handle_share(self, parameters) -> None:
        if not parameters:
            return
        response_topic = parameters[0]
        lease_time = parse_number(
            parameters[1] if len(parameters) > 1 else None,
            DEFAULT_LEASE_TIME)
        lease = self._leases.get(response_topic)
        if lease is not None:
            lease.extend(lease_time)
        else:
            self._leases[response_topic] = Lease(
                self.service.process.event, lease_time, response_topic,
                lease_expired_handler=self._lease_expired)
            self._publish_full_sync(response_topic)

    def _lease_expired(self, response_topic) -> None:
        self._leases.pop(response_topic, None)

    def _publish_full_sync(self, response_topic: str) -> None:
        publish = self.service.process.publish
        items = _flatten(self.share)
        publish(response_topic, generate("item_count", [len(items)]))
        for name, value in items:
            publish(response_topic, generate("add", [name, value]))
        publish(response_topic,
                generate("sync", [self.service.topic_state]))
        _share_counters()[0].inc(len(items) + 2)

    # -- local API ---------------------------------------------------------

    def get(self, name: str):
        return _get_nested(self.share, name)

    def update(self, name: str, value) -> None:
        _set_nested(self.share, name, value)
        # an immediate update SUPERSEDES any pending staged value for
        # the same key: a deferred delta flush must not later overwrite
        # this broadcast with a stale value, and the unchanged-scalar
        # suppression must judge future stages against THIS value
        self._staged.pop(name, None)
        if isinstance(value, (int, float, str, bool)):
            self._last_flushed[name] = value
        else:
            self._last_flushed.pop(name, None)
        self._broadcast("update", name, value)

    def remove(self, name: str) -> None:
        _remove_nested(self.share, name)
        self._staged.pop(name, None)   # a staged write must not resurrect it
        # forget the published shadow too: re-staging the key with its
        # pre-remove value must publish (consumers dropped the key)
        self._last_flushed.pop(name, None)
        self._broadcast("remove", name, None)

    def stage(self, name: str, value, force: bool = False) -> None:
        """Coalesced update: the local share (and change handlers) see
        the value NOW; the lease publishes fold into one delta payload
        per event-loop tick (flush rides the owning actor's mailbox, so
        a registration/stream-churn storm drains before the flush
        runs).  Use for high-churn keys (service_count, load gauges,
        telemetry summaries); update() stays the immediate path.
        `force` publishes the key even when its scalar value is
        unchanged -- heartbeat keys that refresh a consumer's
        staleness clock (ECConsumer.last_update) must hit the wire."""
        _set_nested(self.share, name, value)
        self._staged[name] = value
        if force:
            self._forced.add(name)
        _share_counters()[2].inc()
        for handler in self._change_handlers:
            handler("update", name, value)
        self._schedule_flush()

    def _schedule_flush(self) -> None:
        if self._flush_scheduled:
            return
        self._flush_scheduled = True
        post = getattr(self.service, "post_message", None)
        if post is not None:
            # the flush message queues BEHIND whatever churn is already
            # in the mailbox: one delta per drained burst
            post("_ec_flush_staged", [])
        else:
            event = self.service.process.event

            def fire():
                event.remove_timer_handler(fire)
                self.flush_staged()

            event.add_timer_handler(fire, 0.005)

    def flush_staged(self) -> None:
        self._flush_scheduled = False
        staged, self._staged = self._staged, {}
        forced, self._forced = self._forced, set()
        if not staged:
            return
        payload_dict = {}
        for name, value in staged.items():
            if (name not in forced
                    and isinstance(value, (int, float, str, bool))
                    and name in self._last_flushed
                    and self._last_flushed.get(name) == value):
                continue   # unchanged scalar: nothing to sync
            payload_dict[name] = value
            if isinstance(value, (int, float, str, bool)):
                self._last_flushed[name] = value
        if not payload_dict or not self._leases:
            return
        publish = self.service.process.publish
        payload = generate("delta", [payload_dict])
        publishes, delta_publishes, _ = _share_counters()
        for response_topic in list(self._leases):
            publish(response_topic, payload)
            publishes.inc()
        delta_publishes.inc()

    def _broadcast(self, command: str, name: str, value) -> None:
        publish = self.service.process.publish
        parameters = [name] if value is None else [name, value]
        payload = generate(command, parameters)
        publishes = _share_counters()[0]
        for response_topic in list(self._leases):
            publish(response_topic, payload)
            publishes.inc()
        for handler in self._change_handlers:
            handler(command, name, value)

    def terminate(self) -> None:
        for lease in self._leases.values():
            lease.terminate()
        self._leases.clear()
        self._staged.clear()


class ECConsumer:
    _ids = itertools.count()

    def __init__(self, process, cache: dict, producer_topic_path: str,
                 filter_expression: str = "*",
                 lease_time: float = DEFAULT_LEASE_TIME):
        self.process = process
        self.cache = cache
        self.producer_topic_path = producer_topic_path
        self.filter_expression = filter_expression
        self.lease_time = lease_time
        self.synced = False
        # monotonic timestamp of the LAST producer message (add/update/
        # remove/sync): consumers that must distinguish "live mirror"
        # from "stale snapshot of a wedged producer" (the serving
        # gateway's replica load view) age entries against this
        self.last_update: float | None = None
        self._expected_items = None
        self._change_handlers: list = []
        self.consumer_id = next(self._ids)
        self.response_topic = (
            f"{process.topic_path_process}/0/ec/{self.consumer_id}")
        process.add_message_handler(self._response_handler,
                                    self.response_topic)
        self._lease = Lease(
            process.event, lease_time, self.response_topic,
            lease_extend_handler=self._extend_share,
            automatic_extend=True)
        self._send_share_request()

    def add_change_handler(self, handler) -> None:
        self._change_handlers.append(handler)

    def _send_share_request(self) -> None:
        self.process.publish(
            f"{self.producer_topic_path}/control",
            generate("share", [self.response_topic, self.lease_time,
                               self.filter_expression]))

    def _extend_share(self, lease_time, lease_uuid) -> None:
        self._send_share_request()

    def _response_handler(self, topic: str, payload: str) -> None:
        from ..utils import parse, monotonic
        command, parameters = parse(payload)
        self.last_update = monotonic()
        if command == "item_count" and parameters:
            self._expected_items = parse_number(parameters[0], 0)
        elif command in ("add", "update") and len(parameters) >= 2:
            _set_nested(self.cache, parameters[0], parameters[1])
            self._notify(command, parameters[0], parameters[1])
        elif command == "delta" and parameters:
            # coalesced producer flush: one payload, many keys --
            # mirrored per key so change handlers see ordinary updates
            changes = parameters[0]
            if isinstance(changes, dict):
                for name, value in changes.items():
                    _set_nested(self.cache, name, value)
                    self._notify("update", name, value)
        elif command == "remove" and parameters:
            _remove_nested(self.cache, parameters[0])
            self._notify(command, parameters[0], None)
        elif command == "sync":
            self.synced = True
            self._notify("sync", None, None)

    def _notify(self, command, name, value) -> None:
        for handler in list(self._change_handlers):
            handler(self, command, name, value)

    def terminate(self) -> None:
        self._lease.terminate()
        self.process.remove_message_handler(self._response_handler,
                                            self.response_topic)


class ServicesCache:
    """Live mirror of the registrar's service table
    (reference share.py:477-637)."""

    def __init__(self, process):
        self.process = process
        self.services = Services()
        self.state = "empty"  # empty -> loading -> ready
        self._handlers: list[tuple[ServiceFilter, object]] = []
        self._registrar_topic = None
        self._response_topic = (
            f"{process.topic_path_process}/0/services_cache")
        process.connection.add_handler(self._connection_handler)

    def add_handler(self, handler, service_filter: ServiceFilter) -> None:
        """handler(command, ServiceFields) for "add"/"remove" events matching
        the filter; existing matches replay as "add" immediately."""
        self._handlers.append((service_filter, handler))
        for fields in self.services.filter_services(service_filter):
            handler("add", fields)

    def remove_handler(self, handler) -> None:
        self._handlers = [
            (service_filter, existing)
            for service_filter, existing in list(self._handlers)
            if existing is not handler]

    def _connection_handler(self, connection, state) -> None:
        if (state == ConnectionState.REGISTRAR
                and self.process.registrar is not None):
            registrar_topic = self.process.registrar["topic_path"]
            if registrar_topic == self._registrar_topic:
                return
            self._detach_handlers()
            self._registrar_topic = registrar_topic
            self.state = "loading"
            self.process.add_message_handler(
                self._event_handler, f"{registrar_topic}/out")
            self.process.add_message_handler(
                self._response_handler, self._response_topic)
            self.process.publish(
                f"{registrar_topic}/in",
                generate("share",
                         [self._response_topic, "*", "*", "*", "*", "*",
                          "*"]))
        elif state < ConnectionState.REGISTRAR:
            self._detach_handlers()
            self.state = "empty"
            self.services = Services()

    def _detach_handlers(self) -> None:
        """Unhook the previous registrar's topics (failover must not leave
        stale or duplicate subscriptions)."""
        if self._registrar_topic is not None:
            self.process.remove_message_handler(
                self._event_handler, f"{self._registrar_topic}/out")
            self.process.remove_message_handler(
                self._response_handler, self._response_topic)
            self._registrar_topic = None

    def _response_handler(self, topic: str, payload: str) -> None:
        from ..utils import parse
        command, parameters = parse(payload)
        if command == "add" and parameters:
            fields = ServiceFields.from_parameters(parameters)
            self.services.add_service(fields)
            self._notify("add", fields)
        elif command == "sync":
            self.state = "ready"

    def _event_handler(self, topic: str, payload: str) -> None:
        from ..utils import parse
        command, parameters = parse(payload)
        if command == "add" and parameters:
            fields = ServiceFields.from_parameters(parameters)
            self.services.add_service(fields)
            self._notify("add", fields)
        elif command == "remove" and parameters:
            for fields in self.services.remove_service(parameters[0]):
                self._notify("remove", fields)

    def _notify(self, command: str, fields: ServiceFields) -> None:
        # copy: handlers may remove themselves while being notified
        for service_filter, handler in list(self._handlers):
            if service_filter.matches(fields):
                handler(command, fields)


def services_cache_create_singleton(process) -> ServicesCache:
    """One shared registrar mirror per Process (reference
    share.py:639-656): repeated do_command/do_request/remote-element use
    must not accumulate one full cache (plus registrar subscriptions)
    per call.  Stored ON the process so the cache's lifetime is exactly
    the process's (no global registry pinning terminated processes)."""
    cache = getattr(process, "_services_cache_singleton", None)
    if cache is None:
        cache = ServicesCache(process)
        process._services_cache_singleton = cache
    return cache
