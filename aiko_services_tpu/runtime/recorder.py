# Recorder: aggregate distributed log AND metrics topics for
# observability.
#
# Capability parity with the reference Recorder (reference:
# src/aiko_services/main/recorder.py:50-96): subscribes to a log-topic
# wildcard (default "{namespace}/+/+/+/log"), keeps an LRU of per-topic
# ring buffers, and republishes counts through its ECProducer so dashboards
# can watch live.
#
# Beyond the reference: the Recorder also consumes the telemetry plane --
# pipelines publish "(metrics source snapshot)" on their
# "{topic_path}/metrics" topic (observe.PipelineTelemetry); the Recorder
# keeps the LATEST snapshot per source and merges them associatively into
# one fleet view (observe.merge_snapshots), so a dashboard or operator
# asks ONE service for cluster-wide counters/histograms.
#
# Fault-tolerance plane: pipelines dead-letter error-released frames on
# "{topic_path}/dead_letter" (inputs descriptor + diagnostic + trace id,
# pipeline.py _dead_letter); the Recorder keeps a bounded ring of parsed
# dead letters so operators inspect WHAT failed, WHERE, and under WHICH
# trace without grepping logs.

from __future__ import annotations

from collections import deque

from ..observe.metrics import merge_snapshots, parse_metrics_payload
from ..utils import LRUCache, generate, get_logger, parse
from .actor import Actor
from .share import ECProducer

__all__ = ["Recorder"]

_LOGGER = get_logger("recorder")
SERVICE_PROTOCOL_RECORDER = "recorder:0"
RING_SIZE = 128          # reference logger ring, utilities/logger.py:137
TOPIC_CACHE_SIZE = 64
METRICS_CACHE_SIZE = 64  # latest snapshot per publishing service


class Recorder(Actor):
    def __init__(self, process, name: str = "recorder",
                 log_topic_pattern: str | None = None,
                 metrics_topic_pattern: str | None = None,
                 dead_letter_topic_pattern: str | None = None,
                 ring_size: int = RING_SIZE):
        super().__init__(process, name,
                         protocol=SERVICE_PROTOCOL_RECORDER)
        self.log_topic_pattern = (
            log_topic_pattern or f"{process.namespace}/+/+/+/log")
        self.metrics_topic_pattern = (
            metrics_topic_pattern or f"{process.namespace}/+/+/+/metrics")
        self.dead_letter_topic_pattern = (
            dead_letter_topic_pattern
            or f"{process.namespace}/+/+/+/dead_letter")
        self.ring_size = ring_size
        self.topic_rings = LRUCache(TOPIC_CACHE_SIZE)
        self.metrics_snapshots = LRUCache(METRICS_CACHE_SIZE)
        self.dead_letter_ring = deque(maxlen=ring_size)
        self.share.update({"topic_count": 0, "record_count": 0,
                           "metrics_source_count": 0,
                           "metrics_update_count": 0,
                           "dead_letter_count": 0})
        self._record_count = 0
        self._metrics_update_count = 0
        self._dead_letter_count = 0
        self.add_message_handler(self._log_handler, self.log_topic_pattern)
        self.add_message_handler(self._metrics_handler,
                                 self.metrics_topic_pattern)
        self.add_message_handler(self._dead_letter_handler,
                                 self.dead_letter_topic_pattern)

    def _log_handler(self, topic: str, payload: str) -> None:
        ring = self.topic_rings.get(topic)
        if ring is None:
            ring = deque(maxlen=self.ring_size)
            self.topic_rings.put(topic, ring)
            self.ec_producer.update("topic_count", len(self.topic_rings))
        ring.append(payload)
        self._record_count += 1
        if self._record_count % 16 == 0:  # rate-limit EC chatter
            self.ec_producer.update("record_count", self._record_count)

    def _metrics_handler(self, topic: str, payload: str) -> None:
        decoded = parse_metrics_payload(payload)
        if decoded is None:
            _LOGGER.debug("undecodable metrics payload on %s", topic)
            return
        source, snapshot = decoded
        new_source = self.metrics_snapshots.get(source) is None
        self.metrics_snapshots.put(source, snapshot)
        self._metrics_update_count += 1
        if new_source:
            self.ec_producer.update("metrics_source_count",
                                    len(self.metrics_snapshots))
        if self._metrics_update_count % 16 == 0:  # rate-limit EC chatter
            self.ec_producer.update("metrics_update_count",
                                    self._metrics_update_count)

    def _dead_letter_handler(self, topic: str, payload: str) -> None:
        """One failed frame's evidence: (dead_letter meta descriptor)
        from a pipeline's fault-tolerance layer.  Stored parsed (topic,
        meta, inputs-descriptor) so dead_letters() is directly
        inspectable; every dead letter counts even when the ring
        evicts."""
        try:
            command, parameters = parse(
                payload if isinstance(payload, str) else str(payload))
        except ValueError:
            _LOGGER.debug("undecodable dead letter on %s", topic)
            return
        if command != "dead_letter" or not parameters:
            return
        meta = parameters[0] if isinstance(parameters[0], dict) else {}
        descriptor = (parameters[1] if len(parameters) > 1
                      and isinstance(parameters[1], dict) else {})
        self.dead_letter_ring.append((topic, meta, descriptor))
        self._dead_letter_count += 1
        self.ec_producer.update("dead_letter_count",
                                self._dead_letter_count)

    def records(self, topic: str) -> list:
        ring = self.topic_rings.get(topic)
        return list(ring) if ring is not None else []

    def topics(self) -> list:
        return list(self.topic_rings.keys())

    # -- telemetry views ---------------------------------------------------

    def dead_letters(self) -> list:
        """Newest-last (topic, meta, inputs-descriptor) tuples from the
        fleet's dead-letter topics."""
        return list(self.dead_letter_ring)

    def deadletters(self, response_topic, count="64") -> None:
        """Wire query for the dead-letter ring: `(deadletters
        response_topic [count])` on /in answers with the Storage-style
        paged shape -- "(item_count N)" then N "(item <json>)" records,
        each {"index", "topic", "meta", "descriptor"} -- the surface
        `aiko deadletter ls|replay` drains after a recovered outage.
        Indexes are ring positions (newest last), stable between ls and
        replay as long as no new dead letter lands between the two."""
        try:
            count = int(float(count))
        except (TypeError, ValueError):
            count = 64
        entries = list(self.dead_letter_ring)
        first = max(0, len(entries) - count)
        publish = self.process.publish
        import json
        publish(response_topic,
                generate("item_count", [len(entries) - first]))
        for index in range(first, len(entries)):
            topic, meta, descriptor = entries[index]
            publish(response_topic, generate("item", [json.dumps(
                {"index": index, "topic": topic, "meta": meta,
                 "descriptor": descriptor})]))

    def metrics_sources(self) -> list:
        return list(self.metrics_snapshots.keys())

    def metrics_for(self, source: str) -> dict | None:
        return self.metrics_snapshots.get(source)

    def merged_metrics(self) -> dict:
        """One fleet-wide snapshot: every source's latest, merged
        (counters add, histograms add bucket-wise)."""
        merged = {"counters": {}, "gauges": {}, "histograms": {}}
        for source in self.metrics_snapshots.keys():
            snapshot = self.metrics_snapshots.get(source)
            if snapshot:
                merged = merge_snapshots(merged, snapshot)
        return merged

    def stop(self) -> None:
        # flush the FINAL record/metrics counts: the modulo-16 rate
        # limit otherwise leaves the last published value stale by up
        # to 15 records
        self.ec_producer.update("record_count", self._record_count)
        self.ec_producer.update("metrics_update_count",
                                self._metrics_update_count)
        self.remove_message_handler(self._log_handler,
                                    self.log_topic_pattern)
        self.remove_message_handler(self._metrics_handler,
                                    self.metrics_topic_pattern)
        self.remove_message_handler(self._dead_letter_handler,
                                    self.dead_letter_topic_pattern)
        super().stop()
