# Recorder: aggregate distributed log topics for observability.
#
# Capability parity with the reference Recorder (reference:
# src/aiko_services/main/recorder.py:50-96): subscribes to a log-topic
# wildcard (default "{namespace}/+/+/+/log"), keeps an LRU of per-topic
# ring buffers, and republishes counts through its ECProducer so dashboards
# can watch live.

from __future__ import annotations

from collections import deque

from ..utils import LRUCache, get_logger
from .actor import Actor
from .share import ECProducer

__all__ = ["Recorder"]

_LOGGER = get_logger("recorder")
SERVICE_PROTOCOL_RECORDER = "recorder:0"
RING_SIZE = 128          # reference logger ring, utilities/logger.py:137
TOPIC_CACHE_SIZE = 64


class Recorder(Actor):
    def __init__(self, process, name: str = "recorder",
                 log_topic_pattern: str | None = None,
                 ring_size: int = RING_SIZE):
        super().__init__(process, name,
                         protocol=SERVICE_PROTOCOL_RECORDER)
        self.log_topic_pattern = (
            log_topic_pattern or f"{process.namespace}/+/+/+/log")
        self.ring_size = ring_size
        self.topic_rings = LRUCache(TOPIC_CACHE_SIZE)
        self.share.update({"topic_count": 0, "record_count": 0})
        self._record_count = 0
        self.add_message_handler(self._log_handler, self.log_topic_pattern)

    def _log_handler(self, topic: str, payload: str) -> None:
        ring = self.topic_rings.get(topic)
        if ring is None:
            ring = deque(maxlen=self.ring_size)
            self.topic_rings.put(topic, ring)
            self.ec_producer.update("topic_count", len(self.topic_rings))
        ring.append(payload)
        self._record_count += 1
        if self._record_count % 16 == 0:  # rate-limit EC chatter
            self.ec_producer.update("record_count", self._record_count)

    def records(self, topic: str) -> list:
        ring = self.topic_rings.get(topic)
        return list(ring) if ring is not None else []

    def topics(self) -> list:
        return list(self.topic_rings.keys())

    def stop(self) -> None:
        self.remove_message_handler(self._log_handler,
                                    self.log_topic_pattern)
        super().stop()
