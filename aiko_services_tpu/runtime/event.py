# Event engine: the single-threaded cooperative scheduler every service,
# actor, and pipeline runs on.
#
# Capability parity with the reference event engine (reference:
# src/aiko_services/main/event.py:72-323): periodic timer handlers, named
# mailboxes with registration-order priority (first-added drains first),
# a shared typed queue, and "flat-out" handlers invoked whenever the loop is
# otherwise idle.
#
# Redesigned for latency: the reference loop polls on a fixed 10 ms sleep,
# capping dispatch at ~100 Hz and pipeline frame rates at ~50 Hz
# (reference event.py:281,311-313; SURVEY.md section 6).  This engine blocks
# on a condition variable and wakes exactly when work arrives or a timer is
# due, so dispatch latency is microseconds and throughput is bounded by the
# handlers, not the scheduler.

from __future__ import annotations

import heapq
import itertools
import threading
import traceback
from collections import OrderedDict, deque

from ..utils import get_logger, monotonic

__all__ = ["EventEngine", "Mailbox"]

_LOGGER = get_logger("event")
_FLATOUT_MIN_INTERVAL = 0.001  # ~1 kHz cap (reference event.py:58-59)


class Mailbox:
    __slots__ = ("name", "handler", "items", "high_water")

    def __init__(self, name: str, handler):
        self.name = name
        self.handler = handler
        self.items: deque = deque()
        self.high_water = 0

    def put(self, item) -> None:
        self.items.append(item)
        if len(self.items) > self.high_water:
            self.high_water = len(self.items)
            if self.high_water % 64 == 0:
                _LOGGER.warning(
                    "Mailbox %s backlog growing: %d items",
                    self.name, self.high_water)


class _Timer:
    __slots__ = ("handler", "period", "deadline", "cancelled")

    def __init__(self, handler, period: float, deadline: float):
        self.handler = handler
        self.period = period
        self.deadline = deadline
        self.cancelled = False


class EventEngine:
    """One engine per Process; loop() is the application thread."""

    def __init__(self, name: str = "event"):
        self.name = name
        self._condition = threading.Condition()
        self._timers: list[tuple[float, int, _Timer]] = []
        self._timer_sequence = itertools.count()
        self._timers_by_handler: dict = {}
        self._mailboxes: OrderedDict[str, Mailbox] = OrderedDict()
        self._queue: deque = deque()
        self._queue_handlers: dict[str, list] = {}
        self._flatout_handlers: list = []
        self._terminated = False
        self._loop_thread: threading.Thread | None = None

    # -- handler registration (thread-safe) --------------------------------

    def add_timer_handler(self, handler, period: float,
                          immediate: bool = False) -> None:
        deadline = monotonic() + (0.0 if immediate else period)
        timer = _Timer(handler, period, deadline)
        with self._condition:
            previous = self._timers_by_handler.get(handler)
            if previous is not None:  # re-add replaces: cancel the old timer
                previous.cancelled = True
            self._timers_by_handler[handler] = timer
            heapq.heappush(
                self._timers, (deadline, next(self._timer_sequence), timer))
            self._condition.notify()

    def remove_timer_handler(self, handler) -> None:
        with self._condition:
            timer = self._timers_by_handler.pop(handler, None)
            if timer is not None:
                timer.cancelled = True

    def add_mailbox_handler(self, handler, mailbox_name: str) -> None:
        with self._condition:
            if mailbox_name in self._mailboxes:
                self._mailboxes[mailbox_name].handler = handler
            else:
                self._mailboxes[mailbox_name] = Mailbox(mailbox_name, handler)
            self._condition.notify()

    def remove_mailbox_handler(self, mailbox_name: str) -> None:
        with self._condition:
            self._mailboxes.pop(mailbox_name, None)

    def mailbox_put(self, mailbox_name: str, item) -> None:
        with self._condition:
            mailbox = self._mailboxes.get(mailbox_name)
            if mailbox is None:  # create-on-demand; handler may attach later
                mailbox = self._mailboxes[mailbox_name] = Mailbox(
                    mailbox_name, None)
            mailbox.put(item)
            self._condition.notify()

    def add_queue_handler(self, handler, item_types=("default",)) -> None:
        with self._condition:
            for item_type in item_types:
                self._queue_handlers.setdefault(item_type, []).append(handler)

    def remove_queue_handler(self, handler, item_types=("default",)) -> None:
        with self._condition:
            for item_type in item_types:
                handlers = self._queue_handlers.get(item_type, [])
                if handler in handlers:
                    handlers.remove(handler)

    def queue_put(self, item, item_type: str = "default") -> None:
        with self._condition:
            self._queue.append((item, item_type))
            self._condition.notify()

    def add_flatout_handler(self, handler) -> None:
        with self._condition:
            self._flatout_handlers.append(handler)
            self._condition.notify()

    def remove_flatout_handler(self, handler) -> None:
        with self._condition:
            if handler in self._flatout_handlers:
                self._flatout_handlers.remove(handler)

    # -- loop --------------------------------------------------------------

    def loop(self) -> None:
        self._loop_thread = threading.current_thread()
        last_flatout = 0.0
        while True:
            with self._condition:
                if self._terminated:
                    return
                work = self._next_work_locked()
                if work is None:
                    timeout = self._wait_timeout_locked()
                    self._condition.wait(timeout)
                    continue
            kind, payload = work
            now = monotonic()
            if kind == "timer":
                timer = payload
                self._invoke(timer.handler)
                with self._condition:
                    if not timer.cancelled:
                        timer.deadline = now + timer.period
                        heapq.heappush(
                            self._timers,
                            (timer.deadline, next(self._timer_sequence),
                             timer))
            elif kind == "queue":
                item, item_type = payload
                for handler in self._queue_handlers.get(item_type, []):
                    self._invoke(handler, item)
            elif kind == "mailbox":
                mailbox, item = payload
                if mailbox.handler is not None:
                    self._invoke(mailbox.handler, mailbox.name, item)
            elif kind == "flatout":
                if now - last_flatout < _FLATOUT_MIN_INTERVAL:
                    threading.Event().wait(
                        _FLATOUT_MIN_INTERVAL - (now - last_flatout))
                last_flatout = monotonic()
                for handler in list(self._flatout_handlers):
                    self._invoke(handler)

    def _next_work_locked(self):
        """Pick the next unit of work.  Priority: due timers, queue items,
        mailboxes (registration order -- control before in, reference
        event.py:200,289-303), then flat-out handlers."""
        now = monotonic()
        while self._timers:
            deadline, _, timer = self._timers[0]
            if timer.cancelled:
                heapq.heappop(self._timers)
                continue
            if deadline <= now:
                heapq.heappop(self._timers)
                return ("timer", timer)
            break
        if self._queue:
            return ("queue", self._queue.popleft())
        for mailbox in self._mailboxes.values():
            if mailbox.items and mailbox.handler is not None:
                return ("mailbox", (mailbox, mailbox.items.popleft()))
        if self._flatout_handlers:
            return ("flatout", None)
        return None

    def _wait_timeout_locked(self):
        while self._timers and self._timers[0][2].cancelled:
            heapq.heappop(self._timers)
        if not self._timers:
            return None
        return max(0.0, self._timers[0][0] - monotonic())

    def _invoke(self, handler, *args) -> None:
        try:
            handler(*args)
        except SystemExit:
            raise
        except Exception:
            _LOGGER.error("Handler %r failed:\n%s",
                          handler, traceback.format_exc())

    def loop_in_thread(self) -> threading.Thread:
        thread = threading.Thread(
            target=self.loop, name=f"{self.name}-loop", daemon=True)
        thread.start()
        self._loop_thread = thread
        return thread

    def terminate(self) -> None:
        with self._condition:
            self._terminated = True
            self._condition.notify_all()

    @property
    def terminated(self) -> bool:
        return self._terminated

    def on_loop_thread(self) -> bool:
        return threading.current_thread() is self._loop_thread

    def mailbox_high_water(self) -> dict:
        with self._condition:
            return {name: mailbox.high_water
                    for name, mailbox in self._mailboxes.items()}
