from .event import EventEngine, Mailbox                       # noqa: F401
from .lease import Lease                                      # noqa: F401
from .connection import Connection, ConnectionState           # noqa: F401
from .service import (                                        # noqa: F401
    Service, ServiceProtocol, ServiceFields, ServiceFilter, ServiceTags,
    ServiceTopicPath, Services, PROTOCOL_PREFIX,
    SERVICE_PROTOCOL_REGISTRAR, SERVICE_PROTOCOL_PIPELINE,
    SERVICE_PROTOCOL_ACTOR)
from .process import Process, default_process                 # noqa: F401
from .actor import Actor, ActorMessage, ActorTopic            # noqa: F401
from .proxy import (                                        # noqa: F401
    make_proxy, get_public_methods, RemoteProxy, TracingProxy,
    trace_all_methods)
from .share import (                                          # noqa: F401
    ECProducer, ECConsumer, ServicesCache,
    services_cache_create_singleton)
from .registrar import Registrar, RetainedElection            # noqa: F401
from .state import StateMachine, StateMachineError            # noqa: F401
from .process_manager import ProcessManager                   # noqa: F401
from .compile_cache import (                                  # noqa: F401
    cache_stats, compile_cache_dir, disable_compile_cache,
    enable_compile_cache)
from .lifecycle import LifeCycleManager, LifeCycleClient      # noqa: F401
from .storage import (                                        # noqa: F401
    KeyValueStore, Storage, do_command, do_request)
from .recorder import Recorder                                # noqa: F401
