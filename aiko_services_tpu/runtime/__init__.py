from .event import EventEngine, Mailbox                       # noqa: F401
from .lease import Lease                                      # noqa: F401
from .connection import Connection, ConnectionState           # noqa: F401
from .service import (                                        # noqa: F401
    Service, ServiceProtocol, ServiceFields, ServiceFilter, ServiceTags,
    ServiceTopicPath, Services, PROTOCOL_PREFIX,
    SERVICE_PROTOCOL_REGISTRAR, SERVICE_PROTOCOL_PIPELINE,
    SERVICE_PROTOCOL_ACTOR)
from .process import Process, default_process                 # noqa: F401
from .actor import Actor, ActorMessage, ActorTopic            # noqa: F401
from .proxy import make_proxy, get_public_methods, RemoteProxy  # noqa: F401
from .share import ECProducer, ECConsumer, ServicesCache      # noqa: F401
from .registrar import Registrar                              # noqa: F401
