# Persistent compile cache: warm-start replicas skip the compile storm.
#
# BENCH_NOTES characterizes 2-40 s-per-shape XLA compiles through the
# tunnel; a freshly spawned replica that re-traces every shape the fleet
# already serves arrives too late to absorb the load spike that caused
# it to be spawned.  JAX's persistent compilation cache keys serialized
# executables by (HLO, compile options, backend), so every process that
# points at the SAME cache directory deserializes instead of compiling:
# the fleet pays each shape's compile exactly once, and a warm replica's
# time-to-healthy is dominated by weight hand-off + deserialize, not XLA.
#
# This module is the one place that flips the JAX knobs and the one
# place that counts: a jax monitoring listener mirrors the cache's
# hit/miss events into the process-global metrics registry
# (`compile_cache.hits` / `compile_cache.misses` /
# `compile_cache.requests`), so "zero recompiles of fleet-known shapes"
# is a published counter, not a hope.  The autoscaler's warm-start proof
# and the `autoscale` bench block both read cache_stats() deltas.

from __future__ import annotations

import os
import threading

from ..utils import get_logger

__all__ = ["enable_compile_cache", "disable_compile_cache",
           "compile_cache_dir", "cache_stats", "thread_cache_snapshot",
           "thread_cache_delta"]

_LOGGER = get_logger("compile_cache")

ENV_CACHE_DIR = "AIKO_COMPILE_CACHE"

_LOCK = threading.Lock()
_ENABLED_DIR: str | None = None
_LISTENER_INSTALLED = False

# event names are jax-internal but stable across the 0.4.x line; gate
# every use so a rename degrades to uncounted, never to a crash
_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_MISS_EVENT = "/jax/compilation_cache/cache_misses"
_REQUEST_EVENT = "/jax/compilation_cache/compile_requests_use_cache"


def compile_cache_dir() -> str | None:
    """The directory warm starts share: the explicitly enabled one, else
    the AIKO_COMPILE_CACHE environment value (set for spawned replica
    children via ProcessManager's env override)."""
    return _ENABLED_DIR or os.environ.get(ENV_CACHE_DIR) or None


def enable_compile_cache(directory: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at `directory` (default:
    the AIKO_COMPILE_CACHE environment variable) and install the
    hit/miss counter listener.  Idempotent; returns the active directory
    or None when no directory is configured (cache stays off).

    Thresholds are forced to cache EVERYTHING (min compile time 0, no
    minimum entry size): the fleet's hot shapes include sub-second toy
    programs in tests and smoke benches, and a threshold that skips them
    would make the warm-start proof flaky."""
    global _ENABLED_DIR
    directory = directory or os.environ.get(ENV_CACHE_DIR)
    if not directory:
        return None
    directory = os.path.abspath(str(directory))
    with _LOCK:
        _install_listener()
        if _ENABLED_DIR == directory:
            return directory
        try:
            os.makedirs(directory, exist_ok=True)
            import jax
            jax.config.update("jax_compilation_cache_dir", directory)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0)
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", -1)
            # jax initializes its cache object AT MOST ONCE per process:
            # any compile that ran before the directory was configured
            # latches it disabled, and the config update above would be
            # silently ignored.  reset_cache() drops only the in-memory
            # latch (disk entries survive), so the next compile
            # re-initializes against the directory just set
            from jax._src import compilation_cache
            compilation_cache.reset_cache()
        except Exception as error:  # older jax / read-only fs: run cold
            _LOGGER.warning("persistent compile cache unavailable "
                            "(%s); replicas start cold", error)
            return None
        os.environ[ENV_CACHE_DIR] = directory
        _ENABLED_DIR = directory
        _LOGGER.info("persistent compile cache at %s", directory)
        return directory


def disable_compile_cache() -> None:
    """Point JAX back at no cache directory (test hygiene: the config
    is process-global, so a suite that enabled a tmpdir cache must be
    able to hand the next test a cold configuration)."""
    global _ENABLED_DIR
    with _LOCK:
        if _ENABLED_DIR is None and not os.environ.get(ENV_CACHE_DIR):
            return
        _ENABLED_DIR = None
        os.environ.pop(ENV_CACHE_DIR, None)
        try:
            import jax
            jax.config.update("jax_compilation_cache_dir", None)
            from jax._src import compilation_cache
            compilation_cache.reset_cache()
        except Exception:
            pass


def _install_listener() -> None:
    global _LISTENER_INSTALLED
    if _LISTENER_INSTALLED:
        return
    try:
        from jax._src import monitoring
    except ImportError:
        return

    from ..observe.metrics import get_registry

    def _on_event(event: str, **_kwargs) -> None:
        if event == _HIT_EVENT:
            get_registry().counter("compile_cache.hits").inc()
            _bump_thread(0)
        elif event == _MISS_EVENT:
            get_registry().counter("compile_cache.misses").inc()
            _bump_thread(1)
        elif event == _REQUEST_EVENT:
            get_registry().counter("compile_cache.requests").inc()

    monitoring.register_event_listener(_on_event)
    _LISTENER_INSTALLED = True


# hit/miss counts PER THREAD (ident -> [hits, misses]): compiles land
# on the thread that dispatched them, and every virtual Process runs
# its services on its own event-loop thread -- so a replica's bring-up
# can be attributed exactly even while sibling replicas in the same OS
# process compile concurrently (the global counters cannot tell them
# apart)
_THREAD_COUNTS: dict[int, list] = {}


def _bump_thread(index: int) -> None:
    ident = threading.get_ident()
    with _LOCK:  # pairs with thread_cache_snapshot's iteration
        entry = _THREAD_COUNTS.get(ident)
        if entry is None:
            entry = _THREAD_COUNTS.setdefault(ident, [0, 0])
        entry[index] += 1


def thread_cache_snapshot() -> dict:
    """{thread_ident: (hits, misses)} at this moment; diff two
    snapshots over a known thread set to scope a bring-up's compile
    traffic to exactly the threads that ran it."""
    with _LOCK:
        return {ident: (entry[0], entry[1])
                for ident, entry in _THREAD_COUNTS.items()}


def thread_cache_delta(before: dict, after: dict, idents) -> dict:
    """Hits/misses accumulated between two snapshots on `idents` only."""
    hits = misses = 0
    for ident in idents:
        if ident is None:
            continue
        base = before.get(ident, (0, 0))
        now = after.get(ident, (0, 0))
        hits += now[0] - base[0]
        misses += now[1] - base[1]
    return {"hits": hits, "misses": misses}


def cache_stats() -> dict:
    """Current counter values (zeros until the listener sees traffic):
    read before/after a replica bring-up and diff to get that replica's
    compiles_in_window."""
    from ..observe.metrics import get_registry
    registry = get_registry()
    return {
        "dir": compile_cache_dir(),
        "hits": registry.counter("compile_cache.hits").value,
        "misses": registry.counter("compile_cache.misses").value,
        "requests": registry.counter("compile_cache.requests").value,
    }
