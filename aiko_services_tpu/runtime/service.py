# Service layer: discoverable units inside a Process.
#
# Capability parity with the reference service layer (reference:
# src/aiko_services/main/service.py:99-583): every service owns the topic
# quintet {topic_path}/control,in,log,out,state; ServiceProtocol names a
# capability URL + version; ServiceFilter matches on topic/name/protocol/
# transport/owner/tags; the Services container is a two-level dict
# {process_topic -> {service_id -> fields}} with filtered queries.
#
# Design departure: plain classes and explicit registration instead of the
# reference's composition engine (compose_instance "FrankensteinClass",
# reference component.py:50-123) -- SURVEY.md section 7 calls for ABCs.

from __future__ import annotations

from ..utils import get_logger, get_service_logger, dispose_service_logger
from .connection import ConnectionState

__all__ = [
    "ServiceProtocol", "ServiceFields", "ServiceFilter", "ServiceTags",
    "ServiceTopicPath", "Services", "Service",
    "PROTOCOL_PREFIX", "SERVICE_PROTOCOL_REGISTRAR",
    "SERVICE_PROTOCOL_PIPELINE", "SERVICE_PROTOCOL_ACTOR",
]

_LOGGER = get_logger("service")

PROTOCOL_PREFIX = "github.com/aiko_services_tpu/protocol"
SERVICE_PROTOCOL_REGISTRAR = f"{PROTOCOL_PREFIX}/registrar:2"
SERVICE_PROTOCOL_ACTOR = f"{PROTOCOL_PREFIX}/actor:0"
SERVICE_PROTOCOL_PIPELINE = f"{PROTOCOL_PREFIX}/pipeline:0"


class ServiceProtocol:
    """Capability URL "prefix/name:version" (reference service.py:105-138)."""

    def __init__(self, url_prefix: str, name: str, version):
        self.url_prefix = url_prefix
        self.name = name
        self.version = str(version)

    def __str__(self):
        return f"{self.url_prefix}/{self.name}:{self.version}"

    @staticmethod
    def name_version(protocol: str) -> tuple[str, str]:
        tail = protocol.rsplit("/", 1)[-1]
        if ":" in tail:
            name, version = tail.split(":", 1)
            return name, version
        return tail, ""


class ServiceTags:
    """Tags are "key=value" strings (reference service.py:236-252)."""

    @staticmethod
    def get_tag_value(key: str, tags) -> str | None:
        prefix = f"{key}="
        for tag in tags or ():
            if tag.startswith(prefix):
                return tag[len(prefix):]
        return None

    @staticmethod
    def match(required, tags) -> bool:
        if required in ("*", None) or required == []:
            return True
        return all(tag in (tags or ()) for tag in required)


class ServiceTopicPath:
    """Parse "{namespace}/{hostname}/{process_id}/{service_id}"
    (reference service.py:254-330)."""

    def __init__(self, namespace, hostname, process_id, service_id):
        self.namespace = namespace
        self.hostname = hostname
        self.process_id = str(process_id)
        self.service_id = str(service_id)

    @classmethod
    def parse(cls, topic_path: str) -> "ServiceTopicPath | None":
        parts = topic_path.split("/")
        if len(parts) == 4:
            return cls(*parts)
        return None

    @property
    def process_topic_path(self) -> str:
        return f"{self.namespace}/{self.hostname}/{self.process_id}"

    def terse(self) -> str:
        return f"{self.hostname}/{self.process_id}/{self.service_id}"

    def __str__(self):
        return (f"{self.namespace}/{self.hostname}/"
                f"{self.process_id}/{self.service_id}")


class ServiceFields:
    """Registrar record for one service (reference service.py:150-210)."""

    __slots__ = ("topic_path", "name", "protocol", "transport", "owner",
                 "tags")

    def __init__(self, topic_path, name, protocol, transport="loopback",
                 owner="", tags=None):
        self.topic_path = topic_path
        self.name = name
        self.protocol = protocol
        self.transport = transport
        self.owner = owner
        self.tags = list(tags or [])

    def to_parameters(self) -> list:
        return [self.topic_path, self.name, self.protocol, self.transport,
                self.owner, self.tags]

    @classmethod
    def from_parameters(cls, parameters) -> "ServiceFields":
        topic_path, name, protocol, transport, owner = parameters[:5]
        tags = parameters[5] if len(parameters) > 5 else []
        if isinstance(tags, str):
            tags = [tags]
        return cls(topic_path, name, protocol, transport, owner, tags)

    def __repr__(self):
        return (f"ServiceFields({self.topic_path}, {self.name}, "
                f"{self.protocol}, {self.transport}, {self.owner}, "
                f"{self.tags})")


def _field_match(required, actual) -> bool:
    if required in ("*", None):
        return True
    if isinstance(required, str) and ("*" in required or "?" in required):
        import fnmatch
        return fnmatch.fnmatchcase(str(actual), required)
    return required == actual


class ServiceFilter:
    """Wildcard service query (reference service.py:212-234)."""

    def __init__(self, topic_paths="*", name="*", protocol="*",
                 transport="*", owner="*", tags="*"):
        self.topic_paths = topic_paths
        self.name = name
        self.protocol = protocol
        self.transport = transport
        self.owner = owner
        self.tags = tags

    @classmethod
    def from_parameters(cls, parameters) -> "ServiceFilter":
        fields = list(parameters) + ["*"] * (6 - len(parameters))
        return cls(*fields[:6])

    def to_parameters(self) -> list:
        return [self.topic_paths, self.name, self.protocol, self.transport,
                self.owner, self.tags]

    def matches(self, fields: ServiceFields) -> bool:
        if self.topic_paths not in ("*", None):
            topic_paths = (self.topic_paths
                           if isinstance(self.topic_paths, (list, tuple))
                           else [self.topic_paths])
            if fields.topic_path not in topic_paths:
                return False
        return (_field_match(self.name, fields.name)
                and _field_match(self.protocol, fields.protocol)
                and _field_match(self.transport, fields.transport)
                and _field_match(self.owner, fields.owner)
                and ServiceTags.match(self.tags, fields.tags))

    def __repr__(self):
        return f"ServiceFilter({self.to_parameters()})"


class Services:
    """Two-level registry {process_topic -> {service_id -> ServiceFields}}
    (reference service.py:354-490)."""

    def __init__(self):
        self._services: dict[str, dict[str, ServiceFields]] = {}
        self._count = 0

    def add_service(self, fields: ServiceFields) -> None:
        topic = ServiceTopicPath.parse(fields.topic_path)
        if topic is None:
            raise ValueError(f"Bad service topic path: {fields.topic_path}")
        process = self._services.setdefault(topic.process_topic_path, {})
        if topic.service_id not in process:
            self._count += 1
        process[topic.service_id] = fields

    def remove_service(self, topic_path: str) -> list[ServiceFields]:
        """Remove one service; service_id 0 purges the whole process
        (reference registrar.py:334-357)."""
        topic = ServiceTopicPath.parse(topic_path)
        if topic is None:
            return []
        process = self._services.get(topic.process_topic_path)
        if process is None:
            return []
        removed = []
        if topic.service_id == "0":
            removed = list(process.values())
            self._count -= len(process)
            del self._services[topic.process_topic_path]
        elif topic.service_id in process:
            removed = [process.pop(topic.service_id)]
            self._count -= 1
            if not process:
                del self._services[topic.process_topic_path]
        return removed

    def get_service(self, topic_path: str) -> ServiceFields | None:
        topic = ServiceTopicPath.parse(topic_path)
        if topic is None:
            return None
        return self._services.get(
            topic.process_topic_path, {}).get(topic.service_id)

    def filter_services(self, service_filter: ServiceFilter) -> list:
        return [fields
                for process in self._services.values()
                for fields in process.values()
                if service_filter.matches(fields)]

    def __len__(self):
        return self._count

    def __iter__(self):
        for process in self._services.values():
            yield from process.values()


class Service:
    """A discoverable unit inside a Process.

    Owns the topic quintet and registers itself with its process (which
    forwards the registration to the Registrar once discovered).
    """

    def __init__(self, process, name: str, protocol: str = None,
                 tags=None, owner: str = ""):
        self.process = process
        self.name = name
        self.protocol = protocol or SERVICE_PROTOCOL_ACTOR
        self.tags = list(tags or [])
        self.owner = owner
        self.service_id = None      # assigned by process.add_service
        self.topic_path = None
        process.add_service(self)
        # distributed logging (reference logger.py:127-172, process.py:103-
        # 114): records buffer in a ring until the transport connects, then
        # stream to {topic_path}/log; a Recorder or dashboard subscribes
        self.logger, self._log_ring = get_service_logger(self.topic_path)
        import threading
        self._log_tls = threading.local()  # per-thread recursion guard
        if self._log_ring is not None:
            process.connection.add_handler(self._log_connection_handler)

    # topic quintet (reference service.py:535-551)
    @property
    def topic_control(self):
        return f"{self.topic_path}/control"

    @property
    def topic_in(self):
        return f"{self.topic_path}/in"

    @property
    def topic_log(self):
        return f"{self.topic_path}/log"

    @property
    def topic_out(self):
        return f"{self.topic_path}/out"

    @property
    def topic_state(self):
        return f"{self.topic_path}/state"

    def service_fields(self) -> ServiceFields:
        return ServiceFields(
            topic_path=self.topic_path, name=self.name,
            protocol=self.protocol, transport=self.process.transport_kind,
            owner=self.owner, tags=self.tags)

    # -- distributed logging ----------------------------------------------

    def _log_connection_handler(self, connection, state) -> None:
        if connection.is_connected(ConnectionState.TRANSPORT):
            self._log_ring.attach_sink(self._publish_log_record)
        else:
            self._log_ring.detach_sink()

    def _publish_log_record(self, text: str) -> None:
        # per-thread guard: a transport that logs DURING publish must not
        # recurse, while concurrent logging from other threads still flows
        if getattr(self._log_tls, "publishing", False):
            return
        self._log_tls.publishing = True
        try:
            self.process.publish(self.topic_log, text)
        finally:
            self._log_tls.publishing = False

    def add_tags(self, tags) -> None:
        for tag in tags:
            if tag not in self.tags:
                self.tags.append(tag)

    def add_message_handler(self, handler, topic: str,
                            binary: bool = False) -> None:
        self.process.add_message_handler(handler, topic)

    def remove_message_handler(self, handler, topic: str) -> None:
        self.process.remove_message_handler(handler, topic)

    def stop(self) -> None:
        if self._log_ring is not None:
            self.process.connection.remove_handler(
                self._log_connection_handler)
            self._log_ring.detach_sink()
        dispose_service_logger(self.logger)
        self.process.remove_service(self)
