# Timer-based lease (capability parity with reference
# src/aiko_services/main/lease.py:31-83): fires an expiry handler unless
# extended; optionally auto-extends at 80% of the lease period.  Building
# block for stream lifetimes, EC share subscriptions, and lifecycle
# handshakes.

from __future__ import annotations

import hashlib

from ..utils import monotonic

__all__ = ["Lease", "jitter_fraction", "DEFAULT_TIMER_JITTER"]

# default spread for jittered lease timers: up to +10% of the period
DEFAULT_TIMER_JITTER = 0.1


def jitter_fraction(seed, lease_uuid,
                    spread: float = DEFAULT_TIMER_JITTER,
                    salt: str = "lease") -> float:
    """Deterministic per-lease fraction in [0, spread) for the Lease
    `jitter` parameter: a pure hash of (salt, seed, uuid), so runs
    under the same fault-harness seed reproduce the exact timer
    schedule while a burst of leases still spreads out (no
    thundering-herd lockstep).  ONE definition, shared by the pipeline
    stream leases and the serving gateway's stream records."""
    digest = hashlib.blake2b(
        f"{salt}:{seed}:{lease_uuid}".encode(), digest_size=8).digest()
    return (int.from_bytes(digest, "big") / float(1 << 64)) * spread


class Lease:
    def __init__(self, event_engine, lease_time: float, lease_uuid,
                 lease_expired_handler=None, lease_extend_handler=None,
                 automatic_extend: bool = False, jitter: float = 0.0):
        self.event_engine = event_engine
        self.lease_time = lease_time
        self.lease_uuid = lease_uuid
        self.lease_expired_handler = lease_expired_handler
        self.lease_extend_handler = lease_extend_handler
        self.automatic_extend = automatic_extend
        self._expired = False
        self._terminated = False
        self._deadline = monotonic() + lease_time
        if automatic_extend:
            # Extend at 0.8 x period so the lease never lapses while alive
            # (reference lease.py:33,54-56).
            self._timer_period = lease_time * 0.8
            self._timer = self._automatic_extend_timer
        else:
            self._timer_period = lease_time
            self._timer = self._expiry_timer
        # `jitter` stretches the TIMER PERIOD (never the deadline) by a
        # caller-chosen fraction: thousands of leases created in one
        # burst must not run their expiry checks in lockstep every
        # period (a thundering herd on the event loop).  The deadline
        # math is untouched, so expiry semantics only shift by at most
        # one jittered period -- callers pass a DETERMINISTIC fraction
        # (e.g. hashed from the lease uuid + harness seed) so runs
        # reproduce exactly.
        if jitter > 0.0:
            self._timer_period *= 1.0 + jitter
        event_engine.add_timer_handler(self._timer, self._timer_period)

    def _automatic_extend_timer(self) -> None:
        if self._terminated:
            return
        self.extend()
        if self.lease_extend_handler:
            self.lease_extend_handler(self.lease_time, self.lease_uuid)

    def _expiry_timer(self) -> None:
        if self._terminated:
            return
        if monotonic() >= self._deadline:
            self._expired = True
            self.terminate()
            if self.lease_expired_handler:
                self.lease_expired_handler(self.lease_uuid)

    def extend(self, lease_time: float | None = None) -> None:
        if lease_time is not None:
            self.lease_time = lease_time
        self._deadline = monotonic() + self.lease_time

    @property
    def expired(self) -> bool:
        return self._expired

    def terminate(self) -> None:
        if not self._terminated:
            self._terminated = True
            self.event_engine.remove_timer_handler(self._timer)
