# LifeCycleManager / LifeCycleClient: manage fleets of worker processes.
#
# Capability parity with the reference lifecycle layer (reference:
# src/aiko_services/main/lifecycle.py:98-456): a manager creates client
# processes (via ProcessManager), each client announces itself with
# "(add_client topic_path id)" on the manager's control topic when it
# reaches the registrar; the manager tracks clients by handshake lease
# (30 s default, lifecycle.py:74-75), watches each client's share via
# ECConsumer, reaps clients whose handshake or deletion lease lapses, and
# detects removals through registrar remove events.

from __future__ import annotations

from ..utils import generate, get_logger
from .actor import Actor
from .lease import Lease
from .process_manager import ProcessManager
from .proxy import make_proxy
from .service import ServiceFilter
from .share import ECConsumer, services_cache_create_singleton

__all__ = ["LifeCycleManager", "LifeCycleClient"]

_LOGGER = get_logger("lifecycle")
HANDSHAKE_LEASE_TIME = 30.0   # reference lifecycle.py:74-75
DELETION_LEASE_TIME = 5.0     # reference lifecycle.py:259-263


class LifeCycleManager(Actor):
    """Creates and tracks LifeCycleClient processes.

    create_client(command, arguments) spawns a process that must construct
    a LifeCycleClient pointing back at this manager; the client then has
    HANDSHAKE_LEASE_TIME to call add_client on our control topic or it is
    killed.
    """

    def __init__(self, process, name: str,
                 client_change_handler=None,
                 handshake_lease_time: float = HANDSHAKE_LEASE_TIME):
        super().__init__(process, name)
        self.clients: dict = {}          # client_id -> record
        self._client_change_handler = client_change_handler
        self._handshake_lease_time = handshake_lease_time
        self._client_sequence = 0
        self.process_manager = ProcessManager(self._process_exit_handler)
        self.share["client_count"] = 0
        # child exits arrive on the ProcessManager monitor THREAD; defer
        # all state mutation onto the event loop
        process.event.add_queue_handler(self._client_exit_queued,
                                        ["lifecycle_exit"])
        process.event.add_queue_handler(self._client_lost_queued,
                                        ["lifecycle_lost"])
        # a client that crashes WITH LWT (severed broker connection)
        # vanishes from the registrar before -- or instead of -- its OS
        # exit being reaped: watch removals so the record (and any
        # wedged zombie process) is reaped either way.  The bound
        # method is stored ONCE: ServicesCache.remove_handler matches
        # by identity, and a fresh `self._registrar_event` access would
        # never equal the registered object
        self._services_cache = services_cache_create_singleton(process)
        self._registrar_watch = self._registrar_event
        self._services_cache.add_handler(self._registrar_watch,
                                         ServiceFilter())

    # -- creating clients --------------------------------------------------

    def create_client(self, command: str, arguments=(),
                      use_interpreter: bool = True, env=None) -> int:
        """`env` is merged over the parent environment by
        ProcessManager.spawn: the elastic-fleet spawner pins
        JAX_PLATFORMS, the persistent compile-cache directory, and
        telemetry knobs on every replica child this way."""
        client_id = self._client_sequence
        self._client_sequence += 1
        self.clients[client_id] = {
            "state": "spawning", "topic_path": None, "share": {},
            "ec_consumer": None,
            "lease": Lease(self.process.event, self._handshake_lease_time,
                           client_id,
                           lease_expired_handler=self._handshake_expired),
        }
        self.process_manager.spawn(
            client_id, command,
            list(arguments) + [self.topic_path, str(client_id)],
            use_interpreter=use_interpreter, env=env)
        return client_id

    def _handshake_expired(self, client_id) -> None:
        record = self.clients.get(client_id)
        if record is not None and record["state"] == "spawning":
            _LOGGER.warning("Client %s missed handshake: killing",
                            client_id)
            self._remove_client(client_id, kill=True)

    # -- control-topic commands from clients -------------------------------

    def add_client(self, topic_path, client_id) -> None:
        """Client handshake (reference lifecycle.py:190-227; arrives on the
        control topic as "(add_client topic_path id)")."""
        client_id = int(client_id)
        record = self.clients.get(client_id)
        if record is None:
            _LOGGER.warning("add_client for unknown id %s", client_id)
            return
        if record["state"] != "spawning":
            # duplicate handshake (running) is idempotent; a handshake
            # during deletion must NOT cancel the pending deletion
            return
        record["state"] = "running"
        record["topic_path"] = topic_path
        record["lease"].terminate()
        record["lease"] = None
        record["ec_consumer"] = ECConsumer(
            self.process, record["share"], topic_path)
        self._update_share()
        if self._client_change_handler:
            self._client_change_handler("add", client_id)

    # -- removal -----------------------------------------------------------

    def delete_client(self, client_id: int) -> None:
        """Graceful stop: ask the client to terminate, force-kill if it
        lingers past the deletion lease (reference lifecycle.py:259-269)."""
        record = self.clients.get(client_id)
        if record is None:
            return
        if record["topic_path"]:
            make_proxy(self.process, record["topic_path"]).terminate()
        record["state"] = "deleting"
        record["lease"] = Lease(
            self.process.event, DELETION_LEASE_TIME, client_id,
            lease_expired_handler=self._deletion_expired)

    def _deletion_expired(self, client_id) -> None:
        if client_id in self.clients:
            _LOGGER.warning("Client %s ignored terminate: killing",
                            client_id)
            self._remove_client(client_id, kill=True)

    def _process_exit_handler(self, client_id, return_code) -> None:
        # monitor thread -> event loop (no direct mutation here)
        self.process.event.queue_put(client_id, "lifecycle_exit")

    def _client_exit_queued(self, client_id) -> None:
        self._remove_client(client_id, kill=False)

    def _registrar_event(self, command, fields) -> None:
        """ServicesCache callback (message-pump side): a RUNNING
        client's registrar entry vanished -- LWT fired on a severed
        connection, or the service terminated without telling us.
        Defer onto the event loop like the exit path."""
        if command != "remove":
            return
        for client_id, record in list(self.clients.items()):
            if (record["topic_path"] == fields.topic_path
                    and record["state"] == "running"):
                _LOGGER.warning("Client %s lost from registrar (LWT); "
                                "reaping", client_id)
                self.process.event.queue_put(client_id, "lifecycle_lost")

    def _client_lost_queued(self, client_id) -> None:
        # the broker connection died but the OS process may linger as a
        # zombie: kill=True covers both
        self._remove_client(client_id, kill=True)

    def _remove_client(self, client_id, kill: bool) -> None:
        record = self.clients.pop(client_id, None)
        if record is None:
            return
        if record["lease"] is not None:
            record["lease"].terminate()
        if record["ec_consumer"] is not None:
            record["ec_consumer"].terminate()
        self._update_share()
        if self._client_change_handler:
            self._client_change_handler("remove", client_id)
        if kill:
            # synchronous record removal + SIGTERM; the grace wait and
            # SIGKILL escalation run off-thread inside ProcessManager.kill
            self.process_manager.kill(client_id)

    def _update_share(self) -> None:
        if self.ec_producer is not None:
            self.ec_producer.update("client_count", len(self.clients))
        else:
            self.share["client_count"] = len(self.clients)

    def stop(self) -> None:
        self._services_cache.remove_handler(self._registrar_watch)
        for client_id in list(self.clients):
            self._remove_client(client_id, kill=True)
        self.process_manager.terminate()
        super().stop()


class LifeCycleClient(Actor):
    """Worker-side half: announces itself to the manager once the
    registrar connection is up (reference lifecycle.py:355-388)."""

    def __init__(self, process, name: str, manager_topic_path: str,
                 client_id):
        super().__init__(process, name)
        self.manager_topic_path = manager_topic_path
        self.client_id = int(client_id)
        self._announced = False
        # Actor auto-creates the ECProducer the manager watches
        # add_handler replays the current state immediately, so an
        # already-REGISTRAR connection announces exactly once through it
        process.connection.add_handler(self._connection_handler)

    def _connection_handler(self, connection, state) -> None:
        from .connection import ConnectionState
        if state == ConnectionState.REGISTRAR and not self._announced:
            self._announce()

    def _announce(self) -> None:
        self._announced = True
        self.process.publish(
            f"{self.manager_topic_path}/control",
            generate("add_client", [self.topic_path, self.client_id]))

    def terminate(self) -> None:
        """Manager asked us to stop: tear down the whole process."""
        self.process.terminate()
