# Benchmark harness: the five BASELINE.json configurations, measured
# through the real framework path, with MFU per compute stage.
#
#   1 text      single-stage text PipelineElement (CPU-class reference:
#               the reference multitude ceiling was ~50 frames/sec over a
#               localhost MQTT broker, run_small.sh:9,21)
#   2 asr       Whisper-small-shape speech->text element, 1 chip
#   3 detector  YOLOv8n-shape detection element, batched stream
#   4 llm       Llama-family decode: time-to-first-token + tokens/sec,
#               streamed through generate_stream (the serving path)
#   5 pipeline  3-stage multi-modal graph (speech -> LM, vision ->
#               detections) end-to-end
#
# Prints ONE JSON line.  Headline metric = config 5 end-to-end frames/sec.
# vs_baseline: with the pipeline config, the end-to-end AUDIO-REALTIME
# factor divided by the reference's whisper-small single-GPU 6x realtime
# (speech_elements.py:186-192); for subset runs without the pipeline
# config, the ratio over the reference's 50 frames/sec broker ceiling.
# The "baseline" key names which denominator applied.  Per-config
# results ride in "configs".
#
# Env knobs: AIKO_BENCH_SMOKE=1 shrinks models/frame counts for CPU smoke
# runs; AIKO_BENCH_CONFIGS=csv subset (e.g. "llm,pipeline");
# AIKO_BENCH_PEAK_TFLOPS overrides the per-chip peak used for MFU.

from __future__ import annotations

import json
import os
import queue
import random
import sys
import time

REFERENCE_FRAMES_PER_SEC = 50.0  # multitude ceiling, run_small.sh:9
# reference whisper-small on a single GPU: 6x realtime (relative-speed
# table, speech_elements.py:186-192)
REFERENCE_GPU_SPEECH_REALTIME = 6.0
SMOKE = os.environ.get("AIKO_BENCH_SMOKE", "") not in ("", "0")
# sources synthesize in HBM by default (measure model compute, not host
# ingest); AIKO_BENCH_ON_DEVICE=0 reverts to host-synthesized frames
ON_DEVICE = os.environ.get("AIKO_BENCH_ON_DEVICE", "1") != "0"
# pipeline telemetry (metrics + frame tracing) rides every benched
# pipeline unless AIKO_BENCH_TELEMETRY=0 -- the off arm measures the
# instrumentation overhead (BENCH_NOTES records the A/B); the flag is
# published in every config block so A/B JSON is self-describing
TELEMETRY = os.environ.get("AIKO_BENCH_TELEMETRY", "1") != "0"
# --trace <path>: accumulate Chrome-trace events from every benched
# pipeline (the config-5 graph included).  EVERY pipeline-running
# config writes its OWN self-describing artifact named by config
# (<path minus .json>.<config>.json -- definition + parameter
# fingerprint + config block + metrics snapshot embedded in the trace
# metadata, so `aiko tune` replays it with no side-channel files), the
# artifact path is published in that config's block, and the combined
# legacy file at <path> still carries every span
_TRACE_PATH = None
_TRACE_EVENTS: list = []
_TRACE_DROPPED = 0
_TRACE_RUNS: dict = {}  # config label -> {events, metadata, dropped}
# --faults <seed>: the serving config runs under a seeded 1%-frame
# transient fault rate at the detector (on_error: retry recovers every
# poisoned frame), publishing injected/retry/dead-letter counts in its
# config block -- throughput under fault load becomes a measured number.
# Without the flag every fault hook is one is-None check (the <2%
# regression budget of the acceptance gate).
_FAULTS_SEED = None

ELEMENTS = "aiko_services_tpu.elements"


def _local(class_name):
    return {"local": {"module": ELEMENTS, "class_name": class_name}}


def _peak_flops_per_chip():
    import jax
    override = os.environ.get("AIKO_BENCH_PEAK_TFLOPS")
    if override:
        return float(override) * 1e12
    kind = jax.devices()[0].device_kind.lower()
    table = {  # bf16 peak per chip
        "v5 lite": 197e12, "v5e": 197e12, "v5p": 459e12, "v5": 197e12,
        "v6 lite": 918e12, "v6e": 918e12, "v4": 275e12, "v3": 123e12,
        "v2": 45e12,
    }
    for key, value in table.items():
        if key in kind:
            return value
    return None


def _mfu(flops_per_sec, peak):
    if not peak or not flops_per_sec:
        return None
    return round(flops_per_sec / peak, 4)


def _sync(value):
    """REAL device synchronization.  On the tunneled axon backend
    jax.block_until_ready returns at dispatch (measured: a 4k-token
    prefill 'blocks' in 0.1 ms while actual completion takes seconds),
    so timing loops that end with block_until_ready measure dispatch,
    not compute.  A one-element dependent readback forces completion of
    the whole array for ~1 link round-trip, no bulk transfer."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    for leaf in jax.tree_util.tree_leaves(value):
        if hasattr(leaf, "ndim"):
            np.asarray(jnp.ravel(leaf)[:1])
            break
    return value


_BARRIER_JIT = None
_BARRIER_CHUNK = 256


def _barrier(refs):
    """Force completion of EVERY collected device value.  A sync on only
    the LAST dispatched program is NOT a barrier on this runtime:
    independent programs are not serialized by a dependent read of the
    newest one (measured: 60 independent detector groups "complete" in
    9.6 ms/group by last-sync but are genuinely still running).  One
    jitted program folds 32 refs into a single dispatch (a per-ref
    eager slice costs ~10 ms of tunnel dispatch EACH, which would
    swamp the quantity under measurement); the chunk results then
    materialize through one readback."""
    global _BARRIER_JIT
    import jax
    import jax.numpy as jnp
    import numpy as np
    leaves = []
    for value in refs:
        for leaf in jax.tree_util.tree_leaves(value):
            if hasattr(leaf, "ndim"):
                leaves.append(leaf)
                break
    if not leaves:
        return
    if _BARRIER_JIT is None:
        _BARRIER_JIT = jax.jit(lambda arrays: jnp.stack(
            [jnp.ravel(a)[0].astype(jnp.float32) for a in arrays]))
    outs = []
    for index in range(0, len(leaves), _BARRIER_CHUNK):
        chunk = leaves[index:index + _BARRIER_CHUNK]
        while len(chunk) < _BARRIER_CHUNK:  # stable arity: one compile
            chunk.append(chunk[-1])
        outs.append(_BARRIER_JIT(tuple(chunk)))
    np.asarray(outs[0] if len(outs) == 1 else jnp.concatenate(outs))


def _honest_elapsed(start, refs):
    """Wall seconds from `start` until every ref's program has been
    FORCED complete.  Includes the barrier's own dispatch cost (~1-2 ms
    per ref on the tunnel), making the result a conservative LOWER
    bound on throughput -- preferred over subtracting a second-pass
    overhead estimate, whose jitter can exceed the residual backlog and
    turn the correction negative."""
    _barrier(refs)
    return max(time.perf_counter() - start, 1e-9)


def _harvest_trace(pipeline, config_label: str | None = None) -> None:
    """Collect one benched pipeline's frame traces before teardown:
    into the combined file's event list AND into the per-config run
    (self-describing metadata captured here, while the live pipeline
    can still report its definition + metrics snapshot)."""
    if not _TRACE_PATH:
        return
    global _TRACE_DROPPED
    label = config_label or pipeline.definition.name
    if label.startswith("bench_"):
        label = label[len("bench_"):]
    events = pipeline.telemetry.chrome_events()
    _TRACE_EVENTS.extend(events)
    _TRACE_DROPPED += pipeline.telemetry.tracer.dropped
    run = _TRACE_RUNS.setdefault(label, {"events": [], "dropped": 0})
    run["events"].extend(events)
    run["dropped"] += pipeline.telemetry.tracer.dropped
    metadata = pipeline.telemetry.trace_metadata(config_name=label)
    previous = run.get("metadata")
    if previous is not None:
        # several pipelines harvested under ONE config (router
        # replicas + the gateway, serving arms): the metrics snapshot
        # must cover them ALL, not just the last -- counters from a
        # single-replica snapshot would understate an N-replica trace
        # -- and the pid list must name every tracer so the tune
        # loader keeps all of this config's spans (and ONLY them).
        # The gateway's metadata carries no definition: keep the
        # replicas' (tune joins element spans against it)
        from aiko_services_tpu.observe import merge_snapshots
        metadata["metrics"] = merge_snapshots(
            previous.get("metrics") or {}, metadata.get("metrics")
            or {})
        metadata["pids"] = sorted(
            set(previous.get("pids") or [])
            | set(metadata.get("pids") or []))
        for key in ("definition", "fingerprint"):
            if key not in metadata and key in previous:
                metadata[key] = previous[key]
        if previous.get("role") != metadata.get("role"):
            # replicas + gateway under one config: no single role
            # describes the artifact (last-harvested must not win)
            metadata.pop("role", None)
    run["metadata"] = metadata


def _write_config_traces(configs: dict, result: dict) -> dict:
    """One artifact per harvested config, named by config, path
    published in the config block.  Returns the combined-file metadata
    (every run's metadata under a "runs" map)."""
    from aiko_services_tpu.observe import chrome_trace_document
    from aiko_services_tpu.observe.trace import TRACE_METADATA_SCHEMA
    base, ext = os.path.splitext(_TRACE_PATH)
    # harvest label (definition name minus "bench_") -> config key
    config_key_of = {"multimodal": "pipeline_multimodal",
                     "det": "detector"}
    trace_files = {}
    runs_metadata = {}
    for label in sorted(_TRACE_RUNS):
        run = _TRACE_RUNS[label]
        key = config_key_of.get(label, label)
        block = configs.get(key)
        metadata = dict(run.get("metadata") or {})
        if block is not None:
            # the config block is embedded BEFORE trace_file is added
            # to it (no self-reference); tune reads capacity/MFU/peak
            # evidence from it
            metadata["config"] = dict(block)
            metadata["config_name"] = key
        metadata["dropped_frames"] = run["dropped"]
        runs_metadata[label] = metadata
        path = f"{base}.{label}{ext or '.json'}"
        try:
            with open(path, "w") as handle:
                json.dump(chrome_trace_document(run["events"],
                                                metadata=metadata),
                          handle)
        except OSError as error:
            result["trace_error"] = str(error)
            continue
        trace_files[key] = path
        if block is not None:
            block["trace_file"] = path
            block["trace_events"] = len(run["events"])
    if trace_files:
        result["trace_files"] = trace_files
    return {"schema": TRACE_METADATA_SCHEMA, "runs": runs_metadata}


def _run_pipeline(definition, warmup: int, measure: int,
                  ready_key: str, timeout: float = 900,
                  latency_frames: int | None = None,
                  window: int | None = None):
    """Drive a pipeline with its own frame generator.

    Two phases: (1) throughput -- the generator keeps the pipeline full
    (frame_window in flight); (2) latency -- a second stream with
    frame_window=1, so exactly one frame is in the system and t0 ->
    completion is true per-frame service latency, not queueing depth.
    Returns (frames/sec, p50 arrival latency s, amortized drain s per
    latency frame, last outputs).
    """
    import numpy as np

    from aiko_services_tpu.pipeline import create_pipeline
    from aiko_services_tpu.runtime import Process

    if latency_frames is None:
        latency_frames = 5 if SMOKE else 30

    # pipeline-level parameters: telemetry on/off is the measured A/B
    # knob; the long metrics_interval keeps the export timer out of
    # short measurement windows
    definition.setdefault("parameters", {}).setdefault(
        "telemetry", TELEMETRY)
    definition["parameters"].setdefault("metrics_interval", 60.0)
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)
    responses = queue.Queue()
    if window is None:
        window = int(os.environ.get("AIKO_BENCH_WINDOW", "64"))
    pipeline.create_stream("bench", queue_response=responses,
                           grace_time=1800,
                           parameters={"frame_window": window})
    for _ in range(warmup):
        _, _, outputs = responses.get(timeout=timeout)
    if warmup:
        _sync(outputs[ready_key])  # drain once: program order covers all
    start = time.perf_counter()
    refs = []
    for _ in range(measure):
        _, _, outputs = responses.get(timeout=timeout)
        refs.append(outputs.get(ready_key))
    # barrier over EVERY measured frame's output (independent programs
    # are NOT forced by a sync on the last one -- see _barrier); the
    # barrier's own dispatch overhead is measured and subtracted
    elapsed = _honest_elapsed(start, refs)
    pipeline.destroy_stream("bench")

    latencies = []
    lat_refs = []
    lat_responses = queue.Queue()
    pipeline.create_stream(
        "latency", queue_response=lat_responses, grace_time=1800,
        parameters={"frame_window": 1, "count": latency_frames + 2})
    for index in range(latency_frames):
        _, _, lat_outputs = lat_responses.get(timeout=timeout)
        # response-arrival latency: dispatch + graph + host stages.  A
        # per-frame _sync here interacts pathologically with the
        # tunneled runtime (measured: interleaving readbacks with the
        # event loop's dispatch stream inflates every frame to ~16 s,
        # while the same work runs in ms without it), so the device-side
        # residual is measured ONCE as drain time below.
        if "t0" in lat_outputs:
            latencies.append(time.time() - lat_outputs["t0"])
        lat_refs.append(lat_outputs.get(ready_key))
    drain_start = time.perf_counter()
    drain = _honest_elapsed(drain_start, lat_refs)  # device backlog
    pipeline.destroy_stream("latency")
    # harvest this pipeline's frame traces before teardown; every
    # benched graph lands in its own per-config artifact AND the
    # combined Perfetto file (distinct process names per config)
    _harvest_trace(pipeline)
    process.terminate()
    # a stage that drops "t0" would silently degrade p50 into a
    # throughput-derived estimate -- fail loudly instead
    assert latencies, (
        "no t0 timestamps reached the response: latency was not measured")
    p50 = float(np.percentile(latencies[1:] or latencies, 50))
    # drain is reported SEPARATELY (not folded into p50): if the device
    # lagged dispatch, drain/latency_frames is each frame's amortized
    # share of the backlog -- readers see when backlog dominated
    return measure / elapsed, p50, drain / max(latency_frames, 1), outputs


def _latency_fields(p50, drain_pf, digits=2):
    """The reported latency triple: total (arrival + amortized drain),
    and its two components, so readers can see when device backlog
    dominated the measurement."""
    return {"p50_ms": round((p50 + drain_pf) * 1000, digits),
            "p50_arrival_ms": round(p50 * 1000, digits),
            "drain_per_frame_ms": round(drain_pf * 1000, digits)}


# -- config 1: text ----------------------------------------------------------

def _text_definition(measure):
    return {
        "name": "bench_text",
        "graph": ["(source (transform))"],
        "elements": [
            {"name": "source",
             "output": [{"name": "text", "type": "str"},
                        {"name": "t0", "type": "float"}],
             "parameters": {"data_sources": ["hello pipeline world"],
                            "count": measure + 60, "timestamps": True},
             "deploy": _local("TextSource")},
            {"name": "transform",
             "input": [{"name": "text", "type": "str"}],
             "output": [{"name": "text", "type": "str"}],
             "parameters": {"transform": "upper"},
             "deploy": _local("TextTransform")},
        ],
    }


def bench_text():
    measure = 200 if SMOKE else 2000
    definition = _text_definition(measure)
    fps, p50, drain_pf, _ = _run_pipeline(
        definition, warmup=50, measure=measure, ready_key="text")
    return {"frames_per_sec": round(fps, 1),
            "telemetry": TELEMETRY,
            **_latency_fields(p50, drain_pf, digits=3),
            "vs_reference_broker_ceiling": round(
                fps / REFERENCE_FRAMES_PER_SEC, 1)}


# -- config 2: ASR -----------------------------------------------------------

def _asr_definition(batch, seconds, max_tokens, preset, count):
    samples = int(seconds * 16000)  # elements/audio_io SAMPLE_RATE
    return {
        "name": "bench_asr",
        "graph": ["(tone (asr))"],
        "elements": [
            {"name": "tone",
             "output": [{"name": "audio",
                         "type": f"f32[b,{samples}]"},
                        {"name": "t0", "type": "float"}],
             "parameters": {"data_sources": [[440, seconds]],
                            "data_batch_size": batch, "timestamps": True,
                            "on_device": ON_DEVICE,
                            "count": count},
             "deploy": _local("ToneSource")},
            {"name": "asr",
             "input": [{"name": "audio", "type": f"f32[b,{samples}]"}],
             "output": [{"name": "tokens",
                         "type": f"i32[b,{max_tokens}]"}],
             "parameters": {"preset": preset, "max_tokens": max_tokens,
                            # 5 s serving chunks need a 512-frame window,
                            # not whisper's full 30 s (1500): encoder
                            # cost scales with the window
                            "max_frames": 192 if SMOKE else 512,
                            "dtype": ("float32" if SMOKE
                                      else "bfloat16")},
             "deploy": _local("SpeechToText")},
        ],
    }


def bench_asr(peak):
    from aiko_services_tpu.models import asr_flops_per_example
    from aiko_services_tpu.models.configs import (
        WHISPER_SMALL, WHISPER_TINY)
    config = WHISPER_TINY if SMOKE else WHISPER_SMALL
    preset = "whisper_tiny" if SMOKE else "whisper_small"
    # batch 16 amortizes the per-call floor 4x better than batch 4
    # (measured r5: MFU 0.026 -> 0.112, 491 -> 2015 audio-sec/s) at
    # p50 44 ms -- still far under the 5 s chunk cadence
    batch = 2 if SMOKE else int(os.environ.get("AIKO_BENCH_ASR_BATCH",
                                               "16"))
    seconds = 1.0 if SMOKE else 5.0
    max_tokens = 8 if SMOKE else 32
    warmup, measure = (2, 4) if SMOKE else (5, 40)
    definition = _asr_definition(batch, seconds, max_tokens, preset,
                                 warmup + measure + 4)
    fps, p50, drain_pf, _ = _run_pipeline(
        definition, warmup=warmup, measure=measure, ready_key="tokens")
    n_frames = int(seconds * 100) // 2  # mel 10 ms hop, conv /2
    flops = asr_flops_per_example(config, n_frames, max_tokens) * batch
    return {"frames_per_sec_chip": round(fps, 2),
            "telemetry": TELEMETRY,
            "audio_sec_per_sec": round(fps * batch * seconds, 1),
            **_latency_fields(p50, drain_pf),
            "model": preset,
            "batch": batch,
            "mfu": _mfu(fps * flops, peak)}


# -- config 3: detector ------------------------------------------------------

def _detector_definition(batch, size, preset, count):
    return {
        "name": "bench_det",
        "graph": ["(camera (detector))"],
        "elements": [
            {"name": "camera",
             "output": [{"name": "image",
                         "type": f"f32[b,3,{size},{size}]"},
                        {"name": "t0", "type": "float"}],
             "parameters": {"data_sources": [[batch, 3, size, size]],
                            "timestamps": True, "on_device": ON_DEVICE,
                            "count": count},
             "deploy": _local("ImageSource")},
            {"name": "detector",
             "input": [{"name": "image",
                        "type": f"f32[b,3,{size},{size}]"}],
             "output": [{"name": "detections", "type": "dict"}],
             "parameters": {"preset": preset,
                            "dtype": ("float32" if SMOKE
                                      else "bfloat16")},
             "deploy": _local("Detector")},
        ],
    }


def bench_detector(peak):
    from aiko_services_tpu.models import detector_flops_per_image
    from aiko_services_tpu.models.configs import (
        DETECTOR_TOY, YOLOV8N_SHAPE)
    config = DETECTOR_TOY if SMOKE else YOLOV8N_SHAPE
    preset = "toy" if SMOKE else "yolov8n"
    # the detect call has a ~38 ms per-call latency floor for ANY
    # batch <= 32 (BENCH_NOTES detector roofline), so bigger batches
    # win; batch 32 however OOMs: the in-flight working set is images
    # (frame_window 32 x 157 MB = 5 GB) PLUS every queued call's
    # activation footprint (~30 MB/image), together past 16 GiB.
    # 16 is the deployable sweet spot (1,099 images/s measured)
    batch = 2 if SMOKE else int(os.environ.get("AIKO_BENCH_DET_BATCH",
                                               "16"))
    warmup, measure = (2, 6) if SMOKE else (10, 100)
    size = config.image_size
    definition = _detector_definition(batch, size, preset,
                                      warmup + measure + 4)
    fps, p50, drain_pf, _ = _run_pipeline(
        definition, warmup=warmup, measure=measure, ready_key="detections")
    flops = detector_flops_per_image(config) * batch
    return {"frames_per_sec_chip": round(fps, 2),
            "telemetry": TELEMETRY,
            "images_per_sec": round(fps * batch, 1),
            **_latency_fields(p50, drain_pf),
            "model": f"{preset} {size}x{size}",
            "batch": batch,
            "mfu": _mfu(fps * flops, peak)}


# -- config 4: LLM decode ----------------------------------------------------

def bench_llm(peak):
    import jax
    import jax.numpy as jnp

    from aiko_services_tpu.models import (
        count_params, generate_stream, init_params,
        transformer_flops_per_token)
    from aiko_services_tpu.models.configs import LLAMA32_1B, LM_TOY

    config = LM_TOY if SMOKE else LLAMA32_1B
    name = "lm_toy" if SMOKE else "llama32_1b"
    prompt_len = 32 if SMOKE else 128
    max_new = 16 if SMOKE else 128
    batch = 1 if SMOKE else 4
    params = init_params(config, jax.random.PRNGKey(0))
    n_params = count_params(params)
    prompt = jnp.ones((batch, prompt_len), jnp.int32)

    # warmup compiles prefill + decode chunks at the MEASURED cache shape
    # (cache max_len is a compile-time shape: warming with a different
    # max_new would leave the real compile inside the TTFT measurement)
    chunk = 8 if SMOKE else 32
    for _ in generate_stream(params, config, prompt, max_new, chunk=chunk):
        pass

    start = time.perf_counter()
    ttft = None
    produced = 0
    for offset, block in generate_stream(params, config, prompt, max_new,
                                         chunk=chunk):
        if ttft is None:
            ttft = time.perf_counter() - start
        produced += block.shape[1]
    elapsed = time.perf_counter() - start
    tokens_per_sec = produced * batch / elapsed
    decode_flops = transformer_flops_per_token(config, prompt_len)

    def measure_decode(row_params, row_config, scale_batch):
        """tokens/sec for one decode row: warmup pass (compiles this
        batch's shapes), then one timed full generation."""
        scale_prompt = jnp.ones((scale_batch, prompt_len), jnp.int32)
        for _ in generate_stream(row_params, row_config, scale_prompt,
                                 max_new, chunk=chunk):
            pass  # compile at this batch
        scale_start = time.perf_counter()
        scale_produced = 0
        for _, block in generate_stream(row_params, row_config,
                                        scale_prompt, max_new,
                                        chunk=chunk):
            scale_produced += block.shape[1]
        return round(scale_produced * scale_batch
                     / (time.perf_counter() - scale_start), 1)

    # batch-scaling rows: decode throughput vs batch (serving headroom --
    # decode is HBM-bound, so tokens/sec should scale with batch until
    # the KV cache saturates bandwidth)
    scaling = {}
    for scale_batch in ((2,) if SMOKE else (16, 64)):
        scaling[f"batch_{scale_batch}"] = measure_decode(
            params, config, scale_batch)

    # int8 KV cache (kv_dtype="int8"): halved cache HBM and cache-read
    # bandwidth, doubling the feasible decode batch at fixed memory;
    # numerics pinned in tests/test_transformer.py::TestKVCacheInt8
    from dataclasses import replace
    config_q = replace(config, kv_dtype="int8")
    for scale_batch in ((2,) if SMOKE else (128,)):
        scaling[f"batch_{scale_batch}_kv_int8"] = measure_decode(
            params, config_q, scale_batch)

    # weight-only int8 (quantize_weights_int8): halves the weight bytes
    # streamed per step (the dominant term at TTFT-class batch; the
    # residual per-step floor is loop/cache/attention work, so the
    # measured win is ~1.26x, not 2x -- BENCH_NOTES); combined with the
    # int8 KV cache at the big batch.  Numerics pinned in
    # TestWeightOnlyInt8
    from aiko_services_tpu.models import quantize_weights_int8
    params_q = quantize_weights_int8(params, config)
    if SMOKE:
        scaling["batch_2_w8"] = measure_decode(params_q, config, 2)
    else:
        scaling[f"batch_{batch}_w8"] = measure_decode(
            params_q, config, batch)
        scaling["batch_128_w8_kv8"] = measure_decode(
            params_q, config_q, 128)
    return {"model": f"{name} ({n_params / 1e6:.0f}M params)",
            "batch": batch,
            "prompt_len": prompt_len,
            "time_to_first_token_ms": round(ttft * 1000, 1),
            "tokens_per_sec": round(tokens_per_sec, 1),
            "tokens_per_sec_by_batch": scaling,
            "decode_mfu": _mfu(tokens_per_sec * decode_flops, peak)}


# -- config 4d: long-context prefill (SURVEY: long context first-class) -----

def bench_longcontext(peak):
    """Flash-attention prefill at long sequence on the flagship
    architecture: one full causal forward (the serving prefill / scoring
    path).  The reference handles long audio by CHUNKING (5 s windows,
    speech_elements.py:54-83) and has no long-context capability at all;
    this measures the real thing on the chip -- at 16k the quadratic
    attention term is ~1/3 of total FLOPs, so sustained MFU here proves
    the Pallas flash kernel, not just the matmuls."""
    import jax
    import jax.numpy as jnp

    from aiko_services_tpu.models import (
        count_params, forward, init_params, transformer_flops_per_token)
    from aiko_services_tpu.models.configs import LLAMA32_1B, LM_TOY
    from dataclasses import replace

    if SMOKE:
        config, name, lengths, batch = LM_TOY, "lm_toy", (128,), 1
    else:
        # half-depth llama32_1b architecture (activation headroom at 16k)
        config = replace(LLAMA32_1B, n_layers=8, max_seq_len=16384)
        name = "llama32_1b architecture, 8 layers"
        lengths, batch = (4096, 16384), 1
    params = init_params(config, jax.random.PRNGKey(0))
    n_params = count_params(params)
    # jit with a stable identity: raw forward() outside jit re-traces
    # per call (lax.scan compiles each invocation).  Return ONLY the
    # last position's logits: the full (L, 128256) tensor is 8.4 GB at
    # 16k and XLA dead-code-eliminates the unused head positions, so the
    # measurement covers the transformer body + one head row (the
    # serving prefill shape: next-token after the prompt)
    prefill = jax.jit(lambda p, t: forward(p, config, t)[:, -1])
    rows = {}
    for length in lengths:
        tokens = jnp.ones((batch, length), jnp.int32)
        logits = prefill(params, tokens)  # compile
        _sync(logits)
        steps = 2 if SMOKE else 4
        start = time.perf_counter()
        for _ in range(steps):
            logits = prefill(params, tokens)
        _sync(logits)  # program order: all steps complete
        elapsed = time.perf_counter() - start
        tokens_per_sec = steps * batch * length / elapsed
        # causal prefill: average attended context is length/2 (full
        # length would overstate MFU); subtract the per-token head term
        # (2*d*V) since only ONE position's logits are computed
        per_token = (transformer_flops_per_token(config, length // 2)
                     - 2 * config.d_model * config.vocab_size)
        flops = per_token * tokens_per_sec
        rows[f"seq_{length}"] = {
            "tokens_per_sec": round(tokens_per_sec, 1),
            "prefill_ms": round(elapsed / steps * 1000, 1),
            "mfu": _mfu(flops, peak)}
    return {"model": f"{name} ({n_params / 1e6:.0f}M params)",
            "batch": batch, "prefill": rows}


# -- config 4c: training step (beyond the reference: it never trains) -------

def bench_train(peak):
    """make_train_step throughput on the flagship architecture: full
    fwd+bwd+adamw per step.  Training is where the MXU saturates (big
    batched matmuls, no decode memory-wall), so this row carries the
    framework's compute ceiling."""
    import jax
    import jax.numpy as jnp
    import optax

    from aiko_services_tpu.models import (
        count_params, init_params, make_train_step)
    from aiko_services_tpu.models.configs import LLAMA32_1B, LM_TOY
    from dataclasses import replace

    if SMOKE:
        config, name = LM_TOY, "lm_toy"
        batch, seq, steps = 2, 64, 2
    else:
        # 1B-class training on ONE v5e chip: f32 adam moments + grads
        # need headroom, so train a half-depth variant of the llama32_1b
        # architecture (8 layers) at seq 1024
        config = replace(LLAMA32_1B, n_layers=8)
        name = "llama32_1b architecture, 8 layers"
        batch, seq, steps = 4, 1024, 8
    params = init_params(config, jax.random.PRNGKey(0))
    n_params = count_params(params)
    optimizer = optax.adamw(1e-4)
    opt_state = optimizer.init(params)
    # remat sweep knob (ROADMAP #3b): AIKO_BENCH_REMAT names a
    # models.REMAT_POLICIES entry; losses are bit-identical across
    # policies (tested), so sweeping it walks the step-time/HBM
    # frontier toward the >= 0.45 train-MFU target
    remat = os.environ.get("AIKO_BENCH_REMAT", "none")
    train_step = make_train_step(config, optimizer, remat_policy=remat)
    tokens = jnp.ones((batch, seq + 1), jnp.int32)
    params, opt_state, loss = train_step(params, opt_state, tokens)  # compile
    _sync(loss)
    start = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = train_step(params, opt_state, tokens)
    _sync(loss)  # forces the whole dependent step chain to complete
    elapsed = time.perf_counter() - start
    tokens_per_sec = steps * batch * seq / elapsed
    # fwd+bwd ~ 6 * params FLOPs per token (+ attention terms omitted:
    # conservative MFU)
    flops_per_sec = tokens_per_sec * 6 * n_params
    return {"model": f"{name} ({n_params / 1e6:.0f}M params)",
            "batch": batch, "seq_len": seq,
            "remat": remat,
            "tokens_per_sec": round(tokens_per_sec, 1),
            "step_ms": round(elapsed / steps * 1000, 1),
            "train_mfu": _mfu(flops_per_sec, peak),
            "loss_finite": bool(jnp.isfinite(loss))}


# -- config 4b: mesh-sharded decode (BASELINE config 4's sharded shape) -----

_SHARDED_SCRIPT = r"""
import json, os, re, time
from dataclasses import replace
from functools import partial

import jax
import jax.numpy as jnp

from aiko_services_tpu.models import (
    cache_specs, decode_step, generate, init_cache, init_params,
    param_specs)
from aiko_services_tpu.models.configs import LLAMA32_1B
from aiko_services_tpu.parallel import filter_specs, shard_pytree
from aiko_services_tpu.parallel.mesh import create_mesh

# llama32_1b ARCHITECTURE (16 scan layers, 32/8 GQA heads, tied
# embeddings, rope 500k) at reduced width: the virtual CPU mesh measures
# SHARDING overhead/collective structure, not chip FLOPs
config = replace(LLAMA32_1B, vocab_size=32768, d_model=512, d_ff=2048,
                 dtype="bfloat16")
if os.environ.get("AIKO_BENCH_SMOKE", "") not in ("", "0"):
    config = replace(config, vocab_size=4096, d_model=128, d_ff=512,
                     n_layers=4)
mesh = create_mesh({"data": 2, "fsdp": 1, "seq": 1, "model": 4})
params = shard_pytree(init_params(config, jax.random.PRNGKey(0)), mesh,
                      filter_specs(param_specs(config), mesh))
batch, prompt_len, max_new = 4, 32, 16

def fresh_cache():
    return shard_pytree(
        init_cache(config, batch, max_len=prompt_len + max_new), mesh,
        filter_specs(cache_specs(), mesh))

prompt = jnp.ones((batch, prompt_len), jnp.int32)
with jax.set_mesh(mesh):
    tokens, _ = generate(params, config, prompt, max_new,
                         cache=fresh_cache())  # compile
    jax.block_until_ready(tokens)
    start = time.perf_counter()
    tokens, _ = generate(params, config, prompt, max_new,
                         cache=fresh_cache())
    jax.block_until_ready(tokens)
    elapsed = time.perf_counter() - start
    step = jax.jit(partial(decode_step, config=config))
    hlo = step.lower(params, cache=fresh_cache(),
                     token=jnp.ones((batch, 1), jnp.int32),
                     pos=jnp.int32(5)).compile().as_text()
collectives = re.findall(
    r"= \S+ (all-reduce|all-gather|reduce-scatter|all-to-all|"
    r"collective-permute)\(", hlo)
print(json.dumps({
    "tokens_per_sec": round(max_new * batch / elapsed, 1),
    "collectives_per_decode_step": len(collectives),
    "collective_kinds": sorted(set(collectives)),
    "n_layers": config.n_layers,
}))
"""


def bench_llm_sharded():
    """Decode with params sharded by param_specs over a mesh (VERDICT r2
    next-item 4).  No multi-chip hardware exists here, so this runs in a
    subprocess on the virtual 8-device CPU mesh (data 2 x model 4) --
    the numbers characterize the sharded program (collective count per
    decode step, mesh-overhead tokens/s), not chip throughput; the
    driver's dryrun_multichip covers compile+execute of the full
    training step the same way."""
    import subprocess
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8"
                        ).strip()
    # skip the sitecustomize axon/TPU registration: it initializes a
    # backend before these flags apply, leaving one CPU device
    env.pop("PALLAS_AXON_POOL_IPS", None)
    try:
        probe = subprocess.run(
            [sys.executable, "-c", _SHARDED_SCRIPT], env=env,
            capture_output=True, text=True, timeout=600)
    except subprocess.TimeoutExpired:
        return {"error": "sharded decode subprocess timed out (600s)"}
    if probe.returncode != 0:
        tail = (probe.stderr or "").strip().splitlines()[-1:]
        return {"error": f"exit {probe.returncode}"
                + (f": {tail[0]}" if tail else "")}
    result = json.loads(probe.stdout.strip().splitlines()[-1])
    result["mesh"] = "virtual 8-device CPU (data=2, model=4)"
    result["model"] = (
        f"llama32_1b architecture at reduced width "
        f"({result.pop('n_layers')} layers, 32/8 GQA heads, "
        f"tied embeddings)")
    return result


# -- config 5: 3-stage multi-modal pipeline ---------------------------------

def _multimodal_setup(name, batch, micro, max_tokens, max_new,
                      audio_seconds, frame_count):
    """Definition + model configs for the config-5 graph at one
    operating point (rows per frame, frames coalesced per jit call) --
    shared by the throughput (micro 8 / window 64) and latency
    (micro 1 / window 1) configs so the two frontier points measure
    the SAME graph."""
    from aiko_services_tpu.models import configs as model_configs
    from aiko_services_tpu.models.asr import AsrConfig
    from aiko_services_tpu.models.detector import DetectorConfig
    from aiko_services_tpu.models.transformer import TransformerConfig

    if SMOKE:
        image_size = 64
        lm = dict(vocab_size=1024, d_model=256, n_layers=2, n_heads=8,
                  n_kv_heads=4, d_ff=768, max_seq_len=2048,
                  dtype="float32", max_new_tokens=max_new)
        asr = dict(d_model=64, enc_layers=1, dec_layers=1, n_heads=2,
                   vocab_size=1024, max_tokens=max_tokens, max_frames=192,
                   dtype="float32")
        det = dict(n_classes=16, base_channels=8, image_size=image_size,
                   dtype="float32")
        asr_config = AsrConfig(**{k: v for k, v in asr.items()
                                  if k != "max_tokens"})
        lm_config = TransformerConfig(**{k: v for k, v in lm.items()
                                         if k != "max_new_tokens"})
        det_config = DetectorConfig(**det)
    else:
        # the flagship presets, by name (BASELINE.md config 5)
        asr = {"preset": "whisper_small", "max_frames": 512,
               "max_tokens": max_tokens, "dtype": "bfloat16",
               "micro_batch": micro}
        lm = {"preset": "llama32_1b", "dtype": "bfloat16",
              "micro_batch": micro, "max_new_tokens": max_new}
        det = {"preset": "yolov8n", "dtype": "bfloat16",
               "micro_batch": micro}
        from dataclasses import replace
        asr_config = replace(model_configs.WHISPER_SMALL, max_frames=512)
        lm_config = model_configs.LLAMA32_1B
        det_config = model_configs.YOLOV8N_SHAPE
        image_size = det_config.image_size
    # typed tensor ports (analyze/ tensor-spec grammar): the symbolic
    # batch `b` ties every stage to the same coalesced leading axis, and
    # `aiko lint` dry-runs asr/lm/detector under jax.eval_shape against
    # these specs -- the config-5 graph is the shipped proof the
    # shape-flow pass verifies a real multi-stage serving graph
    samples = int(audio_seconds * 16000)
    audio_t = f"f32[b,{samples}]"
    image_t = f"f32[b,3,{image_size},{image_size}]"
    tokens_t = f"i32[b,{max_tokens}]"
    generated_t = f"i32[b,{max_new}]"
    definition = {
        "name": name,
        "graph": ["(sources (asr (text) (lm (reply))) (detector))"],
        "elements": [
            {"name": "sources",
             "output": [{"name": "audio", "type": audio_t},
                        {"name": "image", "type": image_t},
                        {"name": "t0", "type": "float"}],
             "parameters": {"data_sources": [[440, audio_seconds]],
                            "image_shape": [3, image_size, image_size],
                            "data_batch_size": batch,
                            "timestamps": True, "on_device": ON_DEVICE,
                            "count": frame_count},
             "deploy": _local("MultiModalSource")},
            {"name": "asr",
             "input": [{"name": "audio", "type": audio_t}],
             "output": [{"name": "tokens", "type": tokens_t}],
             "parameters": asr, "deploy": _local("SpeechToText")},
            {"name": "text",
             "input": [{"name": "tokens", "type": tokens_t}],
             "output": [{"name": "text", "type": "str"}],
             "parameters": {"workers": 32},
             "deploy": _local("TokensToText")},
            {"name": "lm",
             "input": [{"name": "tokens", "type": tokens_t}],
             "output": [{"name": "generated", "type": generated_t}],
             "parameters": lm, "deploy": _local("LMGenerate")},
            {"name": "reply",
             "input": [{"name": "tokens", "type": generated_t}],
             "output": [{"name": "text", "type": "str"}],
             "map_in": {"tokens": "generated"},
             "map_out": {"text": "reply"},
             "parameters": {"workers": 32},
             "deploy": _local("TokensToText")},
            {"name": "detector",
             "input": [{"name": "image", "type": image_t}],
             "output": [{"name": "detections", "type": "dict"}],
             "parameters": det, "deploy": _local("Detector")},
        ],
    }
    return definition, asr_config, lm_config, det_config


def _multimodal_flops(asr_config, lm_config, det_config, batch,
                      max_tokens, max_new, audio_seconds):
    """Per-frame compute across the three model stages (batch rows
    each)."""
    from aiko_services_tpu.models import (
        asr_flops_per_example, detector_flops_per_image,
        transformer_flops_per_token)
    n_frames = int(audio_seconds * 100) // 2
    # LM: prefill over the prompt + max_new decode steps (per-token
    # flops at the FINAL context slightly overstates the quadratic
    # attention term; negligible at ctx <= 48 on a 1B)
    lm_tokens = max_tokens + max_new
    return batch * (
        asr_flops_per_example(asr_config, n_frames, max_tokens)
        + transformer_flops_per_token(lm_config, lm_tokens) * lm_tokens
        + detector_flops_per_image(det_config))


_MULTIMODAL_STAGES = ("whisper_small -> (text, llama32_1b decode -> "
                      "reply text) + yolov8n-640 -> detections")
_MULTIMODAL_STAGES_SMOKE = ("speech->(text,lm decode) + "
                            "vision->detections (smoke)")


def bench_multimodal(peak):
    """BASELINE config 5 at the NAMED reference-scale stages: the
    whisper_small ASR preset, the llama32_1b LM, and the yolov8n 640 px
    detector -- the same model configs benched individually as configs
    2/3/4 (SMOKE shrinks everything for CPU runs).  Each frame carries
    `batch` audio windows + images; micro_batch coalesces queued frames
    into one jit call per stage.  This is the THROUGHPUT operating
    point; the `latency` config runs the same graph at rows 2 / micro 1
    / window 1 (the two ends of the frontier)."""
    warmup, measure = (2, 8) if SMOKE else (10, 120)
    # 5 s chunks = the reference speech cadence (audio_io.py:455-460)
    audio_seconds = 1.0 if SMOKE else 5.0
    # rows per frame (data_batch_size) x frames coalesced per jit call;
    # env-tunable for scaling experiments.  Measured on v5e round 5
    # (after the jitted coalesce program landed): rows 16 / micro 8 /
    # window 64 -> 18.95 fps, MFU 0.263; micro 4 -> 10.7 fps / 0.149;
    # rows 24 collapsed to 3.2 fps (compile-bound) and micro 16
    # (batch-256 stages) stalled the 900 s response timeout compiling
    batch = 1 if SMOKE else int(os.environ.get("AIKO_BENCH_ROWS", "16"))
    micro = 1 if SMOKE else int(os.environ.get("AIKO_BENCH_MICRO", "8"))
    max_tokens = 16
    # the LM stage DECODES (greedy, one jit: prefill + fori_loop), the
    # reference's chat semantics (elements_llm.py:181-210) -- not a
    # scoring pass
    max_new = 8 if SMOKE else int(os.environ.get("AIKO_BENCH_NEW", "32"))
    definition, asr_config, lm_config, det_config = _multimodal_setup(
        "bench_multimodal", batch, micro, max_tokens, max_new,
        audio_seconds, warmup + measure + 4)
    fps, p50, drain_pf, _ = _run_pipeline(
        definition, warmup=warmup, measure=measure, ready_key="detections")
    flops = _multimodal_flops(asr_config, lm_config, det_config, batch,
                              max_tokens, max_new, audio_seconds)
    return {"frames_per_sec_chip": round(fps, 2),
            "telemetry": TELEMETRY,
            **_latency_fields(p50, drain_pf),
            "audio_seconds_per_frame": audio_seconds,
            "rows_per_frame": batch,
            "audio_realtime_factor": round(
                fps * batch * audio_seconds, 2),
            "tokens_generated_per_frame": batch * max_new,
            "stages": (_MULTIMODAL_STAGES if not SMOKE
                       else _MULTIMODAL_STAGES_SMOKE),
            "micro_batch": micro,
            "mfu": _mfu(fps * flops, peak)}, fps, (p50 + drain_pf), (
                audio_seconds), batch


# -- config 5L: the latency operating point of the same graph ----------------

def bench_latency(peak):
    """The LATENCY end of the config-5 frontier (VERDICT r5 item 2: the
    driver metric is throughput AND p50 frame latency, but only the
    throughput-mode operating point -- 533 ms at micro 8 / window 64 --
    was on record).  Same graph, rows 2 / micro_batch 1 /
    frame_window 1: at most ONE frame in flight end-to-end, so p50 is
    true per-frame service latency (dispatch + graph + host stages),
    not queueing depth.  Together with config 5 this records the
    throughput<->latency frontier the serving scheduler can be operated
    on."""
    warmup, measure = (2, 6) if SMOKE else (5, 40)
    audio_seconds = 1.0 if SMOKE else 5.0
    batch = 1 if SMOKE else 2
    max_tokens = 16
    max_new = 8 if SMOKE else 32
    definition, asr_config, lm_config, det_config = _multimodal_setup(
        "bench_latency", batch, 1, max_tokens, max_new, audio_seconds,
        warmup + measure + 4)
    fps, p50, drain_pf, _ = _run_pipeline(
        definition, warmup=warmup, measure=measure,
        ready_key="detections", window=1)
    flops = _multimodal_flops(asr_config, lm_config, det_config, batch,
                              max_tokens, max_new, audio_seconds)
    result = {"frames_per_sec_chip": round(fps, 2),
              "telemetry": TELEMETRY,
              **_latency_fields(p50, drain_pf),
              "audio_seconds_per_frame": audio_seconds,
              "rows_per_frame": batch,
              "micro_batch": 1,
              "frame_window": 1,
              "operating_point": "latency (one frame in flight)",
              "stages": (_MULTIMODAL_STAGES if not SMOKE
                         else _MULTIMODAL_STAGES_SMOKE),
              "mfu": _mfu(fps * flops, peak)}
    if TELEMETRY:
        # tracing-overhead A/B on the latency operating point: the
        # SAME graph with `telemetry: false` (the AIKO_BENCH_TELEMETRY
        # knob's per-config form) -- the published delta is the cost
        # of metrics + frame tracing per frame, where one frame is in
        # flight and nothing amortizes it
        off_definition, _, _, _ = _multimodal_setup(
            "bench_latency_off", batch, 1, max_tokens, max_new,
            audio_seconds, warmup + measure + 4)
        off_definition.setdefault("parameters", {})["telemetry"] = False
        off_fps, off_p50, off_drain, _ = _run_pipeline(
            off_definition, warmup=warmup, measure=measure,
            ready_key="detections", window=1)
        off_fields = _latency_fields(off_p50, off_drain)
        result["telemetry_off"] = {
            "frames_per_sec_chip": round(off_fps, 2),
            **off_fields,
        }
        result["tracing_overhead_p50_ms"] = round(
            result["p50_ms"] - off_fields["p50_ms"], 2)
    return result


# -- config 6: many-stream serving (multitude) -------------------------------

def _serving_definition(name, size, pipeline_parameters,
                        detector_parameters):
    """The one-node serving graph shared by the multitude (config 6)
    and gateway (`--router`) workloads."""
    return {
        "name": name,
        "parameters": pipeline_parameters,
        "graph": ["(detector)"],
        "elements": [
            {"name": "detector",
             "input": [{"name": "image",
                        "type": f"f32[b,3,{size},{size}]"}],
             "output": [{"name": "detections", "type": "dict"}],
             "parameters": detector_parameters,
             "deploy": _local("Detector")},
        ],
    }


def bench_serving(peak):
    """Multitude-style load: MANY concurrent streams, one small frame
    each, all hitting ONE shared detector element -- the reference's
    actual scale test (multitude/run_small.sh: dozens of processes over
    a broker, ~50 frames/sec ceiling).  Frames are INJECTED per stream
    (requests arriving from outside, no generator threads), so the
    measurement is engine + device, and cross-stream continuous
    batching coalesces them into shared jit calls; the same run with
    micro_batch=1 gives the uncoalesced comparison."""
    import jax
    import jax.numpy as jnp

    from aiko_services_tpu.models import detector_flops_per_image
    from aiko_services_tpu.models.configs import DETECTOR_TOY, YOLOV8N_SHAPE
    from aiko_services_tpu.pipeline import create_pipeline
    from aiko_services_tpu.runtime import Process

    streams_n = 4 if SMOKE else 32
    # 60 frames/stream: a ~1-2 s window per arm -- the 30-frame window
    # was short enough for tunnel jitter to dominate the uncoalesced arm
    # (observed medians 585 vs 1667 frames/s across two round-5 runs)
    per_stream = 4 if SMOKE else 60
    config = DETECTOR_TOY if SMOKE else YOLOV8N_SHAPE
    preset = "toy" if SMOKE else "yolov8n"
    size = config.image_size
    images = [
        jax.random.uniform(jax.random.PRNGKey(index), (1, 3, size, size),
                           jnp.float32)
        for index in range(4)]

    fault_totals = {"injected": 0, "retries": 0, "dead_letters": 0,
                    "frames_errored": 0}

    def run(micro):
        pipeline_parameters = {"telemetry": TELEMETRY,
                               "metrics_interval": 60.0}
        detector_parameters = {"preset": preset,
                               "micro_batch": micro,
                               "dtype": ("float32" if SMOKE
                                         else "bfloat16")}
        if _FAULTS_SEED is not None:
            # transient 1%-frame faults (each poisoned frame fails
            # exactly once); the retry policy must recover every one or
            # the response drain below hangs -- completion IS the gate.
            # Telemetry is FORCED on: the retry/dead-letter counters in
            # the published faults block come from it, and zeros under
            # AIKO_BENCH_TELEMETRY=0 would read as silently lost frames
            pipeline_parameters["telemetry"] = True
            pipeline_parameters["faults"] = (
                f"seed={_FAULTS_SEED};"
                f"element_raise:node=detector:rate=0.01:once=1:times=-1")
            detector_parameters.update(
                {"on_error": "retry", "max_retries": 3,
                 "retry_backoff_ms": 1})
        definition = _serving_definition(
            "bench_serving", size, pipeline_parameters,
            detector_parameters)
        process = Process(transport_kind="loopback")
        pipeline = create_pipeline(process, definition)
        responses = queue.Queue()
        # warm stream: compiles the coalesced (and singleton) shapes
        warm_stream = pipeline.create_stream(
            "warm", queue_response=responses, grace_time=1800)
        for index in range(max(micro, 2)):
            pipeline.create_frame(warm_stream, {"image": images[index % 4]})
        process.run(in_thread=True)
        warm_refs = [responses.get(timeout=900)[2].get("detections")
                     for _ in range(max(micro, 2))]
        _barrier(warm_refs)
        streams = [
            pipeline.create_stream(f"s{index}", queue_response=responses,
                                   grace_time=1800)
            for index in range(streams_n)]
        total = streams_n * per_stream
        start = time.perf_counter()
        # requests land interleaved across streams, as a broker delivers
        for round_index in range(per_stream):
            for stream in streams:
                pipeline.create_frame(
                    stream, {"image": images[round_index % 4]})
        refs = []
        for _ in range(total):
            _, _, outputs = responses.get(timeout=900)
            refs.append(outputs.get("detections"))
        elapsed = _honest_elapsed(start, refs)
        _harvest_trace(pipeline)
        if _FAULTS_SEED is not None:
            stats = (pipeline.faults.stats()
                     if pipeline.faults is not None else {})
            registry = pipeline.telemetry.registry
            fault_totals["injected"] += stats.get("element_raise", 0)
            fault_totals["retries"] += registry.counter(
                "pipeline.retries").value
            fault_totals["dead_letters"] += registry.counter(
                "pipeline.dead_letters").value
            fault_totals["frames_errored"] += registry.counter(
                "pipeline.frames_errored").value
        process.terminate()
        return total / elapsed

    import numpy as np

    micro = 4 if SMOKE else 16
    # the round-4 A/B was ONE trial per arm, coalesced first -- and the
    # driver's run recorded the opposite conclusion from the builder's
    # (speedup 1.95 claimed, 0.37 recorded).  Interleaved repeated
    # trials with ALTERNATING order make order effects and tunnel
    # variance visible as spread instead of silently deciding the
    # verdict; medians decide the speedup.  >= 5 trials per arm with
    # per-trial values PUBLISHED: the round-5 coalesced spread was
    # [1030, 1896] and min/max alone could not show whether that was
    # one outlier or a bimodal distribution (VERDICT r5 item 4)
    trials = 1 if SMOKE else 5
    fps_coalesced, fps_single = [], []
    for trial in range(trials):
        arms = [(micro, fps_coalesced), (1, fps_single)]
        if trial % 2:
            arms.reverse()
        for arm_micro, sink in arms:
            sink.append(run(arm_micro))
    med_coalesced = float(np.median(fps_coalesced))
    med_single = float(np.median(fps_single))
    flops = detector_flops_per_image(config)
    faults_block = (
        {"faults": {"seed": _FAULTS_SEED,
                    "spec": "element_raise detector rate=0.01 once",
                    "telemetry_forced": not TELEMETRY,
                    **fault_totals}}
        if _FAULTS_SEED is not None else {})
    return {
        "streams": streams_n,
        "telemetry": TELEMETRY,
        **faults_block,
        "frames_per_sec_total": round(med_coalesced, 1),
        "coalesced_trials": [round(value, 1) for value in fps_coalesced],
        "coalesced_spread": [round(min(fps_coalesced), 1),
                             round(max(fps_coalesced), 1)],
        "frames_per_sec_uncoalesced": round(med_single, 1),
        "uncoalesced_trials": [round(value, 1) for value in fps_single],
        "uncoalesced_spread": [round(min(fps_single), 1),
                               round(max(fps_single), 1)],
        "coalescing_speedup": round(
            med_coalesced / max(med_single, 1e-9), 2),
        "trials_per_arm": trials,
        "micro_batch": micro,
        "model": f"{preset} {size}x{size}",
        "vs_reference_broker_ceiling": round(
            med_coalesced / REFERENCE_FRAMES_PER_SEC, 1),
        "mfu": _mfu(med_coalesced * flops, peak),
    }


# -- router: the serving config behind the gateway ---------------------------

def bench_router(peak, replicas_n: int):
    """`--router N`: the serving workload fronted by the Gateway with N
    in-process replicas under OPEN-LOOP overload -- frames offered at
    2x the measured aggregate capacity regardless of completions, the
    regime where an unprotected pipeline grows its queue without bound.
    Published numbers: goodput (admitted completions/sec), shed rate,
    and p50/p99 admitted latency (submit -> completion through the
    gateway, each response device-synced before timestamping, so the
    latency is conservative)."""
    import threading

    import jax
    import jax.numpy as jnp
    import numpy as np

    from aiko_services_tpu.models import detector_flops_per_image
    from aiko_services_tpu.models.configs import DETECTOR_TOY, YOLOV8N_SHAPE
    from aiko_services_tpu.pipeline import create_pipeline
    from aiko_services_tpu.runtime import Process
    from aiko_services_tpu.serve import Gateway

    config = DETECTOR_TOY if SMOKE else YOLOV8N_SHAPE
    preset = "toy" if SMOKE else "yolov8n"
    size = config.image_size
    micro = 4 if SMOKE else 16
    streams_n = 4 if SMOKE else 16
    per_stream = 4 if SMOKE else 30
    images = [
        jax.random.uniform(jax.random.PRNGKey(index), (1, 3, size, size),
                           jnp.float32)
        for index in range(4)]

    def definition(name):
        return _serving_definition(
            name, size,
            {"telemetry": TELEMETRY, "metrics_interval": 60.0},
            {"preset": preset, "micro_batch": micro,
             "dtype": "float32" if SMOKE else "bfloat16"})

    # phase 1: ONE replica driven closed-loop to saturation -- the
    # capacity the overload is calibrated against
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition("capacity_probe"))
    responses = queue.Queue()
    warm = pipeline.create_stream("warm", queue_response=responses,
                                  grace_time=1800)
    for index in range(max(micro, 2)):
        pipeline.create_frame(warm, {"image": images[index % 4]})
    process.run(in_thread=True)
    _barrier([responses.get(timeout=900)[2].get("detections")
              for _ in range(max(micro, 2))])
    streams = [pipeline.create_stream(f"s{index}",
                                      queue_response=responses,
                                      grace_time=1800)
               for index in range(streams_n)]
    total = streams_n * per_stream
    start = time.perf_counter()
    for round_index in range(per_stream):
        for stream in streams:
            pipeline.create_frame(stream,
                                  {"image": images[round_index % 4]})
    refs = [responses.get(timeout=900)[2].get("detections")
            for _ in range(total)]
    capacity = total / _honest_elapsed(start, refs)
    process.terminate()

    # phase 2: N replicas behind the gateway, offered 2x aggregate
    # capacity open-loop
    processes, replicas = [], []
    for index in range(replicas_n):
        replica_process = Process(transport_kind="loopback")
        processes.append(replica_process)
        replicas.append(create_pipeline(
            replica_process, definition(f"replica{index}")))
    gateway_process = Process(transport_kind="loopback")
    processes.append(gateway_process)
    policy = (f"max_inflight={4 * micro};"
              f"queue={4 * micro * max(replicas_n, 1)}")
    gateway = Gateway(gateway_process, policy=policy, router_seed=7,
                      telemetry=True, metrics_interval=60.0)
    for replica in replicas:
        gateway.attach_replica(replica)
    for proc in processes:
        proc.run(in_thread=True)

    gateway_responses = queue.Queue()
    for index in range(streams_n):
        gateway.submit_stream(f"g{index}",
                              queue_response=gateway_responses)
    # warm every replica's compiled shapes before the measured window
    for index in range(streams_n):
        gateway.submit_frame(f"g{index}", {"image": images[index % 4]})
    warm_refs = []
    for _ in range(streams_n):
        _, _, outputs, status = gateway_responses.get(timeout=900)
        if status == "ok":
            warm_refs.append(outputs.get("detections"))
    _barrier(warm_refs)

    offered_rate = 2.0 * capacity * replicas_n
    window_s = 1.0 if SMOKE else 3.0
    offered = max(int(offered_rate * window_s), streams_n)
    submit_times = {}
    latencies, ok_refs = [], []
    counts = {"ok": 0, "shed": 0, "error": 0}
    done = threading.Event()

    def drain():
        for _ in range(offered):
            stream_id, frame_id, outputs, status = gateway_responses.get(
                timeout=900)
            if status == "ok":
                _sync(outputs.get("detections"))
                end = time.perf_counter()
                submitted = submit_times.pop((stream_id, frame_id), None)
                if submitted is not None:
                    latencies.append(end - submitted)
                ok_refs.append(outputs.get("detections"))
                counts["ok"] += 1
            else:
                counts[status if status in counts else "error"] += 1
                submit_times.pop((stream_id, frame_id), None)
        done.set()

    drainer = threading.Thread(target=drain, daemon=True)
    drainer.start()
    interval = 1.0 / offered_rate
    start = time.perf_counter()
    # frame ids start AFTER the warm frame (id 0): a reused id would be
    # deduped by the gateway's exactly-once delivery, not re-served
    cursors = {f"g{index}": 1 for index in range(streams_n)}
    for index in range(offered):
        stream_id = f"g{index % streams_n}"
        frame_id = cursors[stream_id]
        cursors[stream_id] += 1
        submit_times[(stream_id, frame_id)] = time.perf_counter()
        gateway.submit_frame(stream_id, {"image": images[index % 4]},
                             frame_id=frame_id)
        ahead = start + (index + 1) * interval - time.perf_counter()
        if ahead > 0:
            time.sleep(ahead)
    done.wait(timeout=900)
    elapsed = _honest_elapsed(start, ok_refs)
    goodput = counts["ok"] / elapsed
    shed_rate = counts["shed"] / max(offered, 1)
    summary = gateway.telemetry.summary()
    for replica in replicas:  # every replica's spans, one router run
        _harvest_trace(replica, config_label="router")
    # the GATEWAY contributes its root spans too (admit-wait, route,
    # shed) -- without them the router trace had no admission story
    # and `aiko tune` could only ever see the replica side
    _harvest_trace(gateway, config_label="router")
    for proc in processes:
        proc.terminate()
    flops = detector_flops_per_image(config)
    return {
        "replicas": replicas_n,
        "streams": streams_n,
        # in-process replicas share the host CPU with the gateway's
        # event loop, so goodput_vs_aggregate_capacity includes that
        # contention -- deployed replicas (own hosts) only pay the
        # gateway's per-frame routing cost
        "topology": "in-process replicas, shared host",
        "policy": policy,
        "model": f"{preset} {size}x{size}",
        "micro_batch": micro,
        "capacity_single_fps": round(capacity, 1),
        "offered_fps": round(offered_rate, 1),
        "offered_frames": offered,
        "goodput_fps": round(goodput, 1),
        "goodput_vs_aggregate_capacity": round(
            goodput / max(capacity * replicas_n, 1e-9), 3),
        "shed_rate": round(shed_rate, 3),
        "errors": counts["error"],
        "p50_admitted_ms": (round(float(np.percentile(
            latencies, 50)) * 1000, 2) if latencies else None),
        "p99_admitted_ms": (round(float(np.percentile(
            latencies, 99)) * 1000, 2) if latencies else None),
        "gateway": summary,
        "mfu": _mfu(goodput * flops, peak),
    }


# -- autoscale: the elastic fleet under a mid-run load doubling --------------

# one spec, three surfaces: the running gateway's autoscaler, the
# definition parameter `aiko lint --bench` checks (AIKO406), and the
# published config block
_AUTOSCALE_POLICY = ("min_replicas=1;max_replicas=2;high_water=0.6;"
                     "low_water=0.01;cooldown=1;interval=0.1")


def bench_autoscale(peak):
    """`autoscale` config: the serving workload behind the gateway with
    the elastic replica fleet enabled.  Closed-loop session load (N
    concurrent bounded sessions, each keeping a window of frames in
    flight) DOUBLES mid-run; the autoscaler must spawn a warm replica
    (persistent compile cache + sibling weight hand-off over the
    transfer plane) and goodput must recover with NO manual replica
    attach.  Published: time-to-healthy for every spawned replica --
    the cold baseline bring-up through the SAME factory vs the warm
    spawn -- plus the warm replica's compile-cache delta
    (`compiles_in_window == 0` is the warm-start proof CI asserts) and
    goodput before/during/after the spike."""
    import shutil
    import tempfile
    import threading

    import jax
    import jax.numpy as jnp
    import numpy as np

    from aiko_services_tpu.models import detector_flops_per_image
    from aiko_services_tpu.models.configs import DETECTOR_TOY, YOLOV8N_SHAPE
    from aiko_services_tpu.runtime import Process, disable_compile_cache
    from aiko_services_tpu.serve import Gateway, InProcessReplicaFactory

    config = DETECTOR_TOY if SMOKE else YOLOV8N_SHAPE
    preset = "toy" if SMOKE else "yolov8n"
    size = config.image_size
    micro = 4 if SMOKE else 16
    streams_n = 4 if SMOKE else 16
    images = [
        jax.random.uniform(jax.random.PRNGKey(index), (1, 3, size, size),
                           jnp.float32)
        for index in range(4)]
    cache_dir = tempfile.mkdtemp(prefix="aiko_compile_cache_")

    def definition(name):
        return _serving_definition(
            name, size,
            {"telemetry": TELEMETRY, "metrics_interval": 60.0,
             "autoscale_policy": _AUTOSCALE_POLICY},
            {"preset": preset, "micro_batch": micro,
             "dtype": "float32" if SMOKE else "bfloat16"})

    factory = InProcessReplicaFactory(
        definition, warmup={"image": images[0]},
        compile_cache=cache_dir)

    # phase 1: replica0 comes up COLD through the same factory the
    # autoscaler will use -- it pays the XLA compiles once (populating
    # the shared cache) and its bring-up is the warm spawn's baseline
    cold_ready = queue.Queue()
    cold_start = time.perf_counter()
    factory.spawn("replica0",
                  ready=lambda handle, info: cold_ready.put(
                      (handle, info)))
    handle0, cold_info = cold_ready.get(timeout=900)
    if handle0 is None:
        raise RuntimeError(f"cold replica bring-up failed: {cold_info}")
    time_to_healthy_cold_ms = (time.perf_counter() - cold_start) * 1000.0

    # phase 2: the gateway fronting replica0; capacity is measured
    # CLOSED-LOOP THROUGH THE GATEWAY (submit on completion), because
    # the offered rates must saturate the serving path the autoscaler
    # watches -- the raw pipeline is faster than the routed path on a
    # shared host, and calibrating against it would just shed
    pipeline = handle0.pipeline
    gateway_process = Process(transport_kind="loopback")
    # sized against the closed-loop session load below: base = N
    # sessions x a `micro` window = 0.5 of one replica's cap (under the
    # 0.6 high watermark), the doubling = 1.0 (over it) -- so the
    # controller fires ON the spike, not during the base phase
    policy = (f"max_inflight={8 * micro};"
              f"queue={16 * micro * streams_n}")
    gateway = Gateway(gateway_process, policy=policy, router_seed=7,
                      telemetry=True, metrics_interval=60.0)
    gateway.attach_replica(pipeline)
    gateway_process.run(in_thread=True)

    gateway_responses = queue.Queue()
    for index in range(streams_n):
        gateway.submit_stream(f"g{index}",
                              queue_response=gateway_responses)
    for index in range(streams_n):
        gateway.submit_frame(f"g{index}", {"image": images[index % 4]})
    warm_refs = []
    for _ in range(streams_n):
        _, _, outputs, status = gateway_responses.get(timeout=900)
        if status == "ok":
            warm_refs.append(outputs.get("detections"))
    _barrier(warm_refs)

    cursors = {f"g{index}": 1 for index in range(streams_n)}

    def submit_next(index):
        stream_id = f"g{index % streams_n}"
        frame_id = cursors[stream_id]
        cursors[stream_id] += 1
        gateway.submit_frame(stream_id, {"image": images[index % 4]},
                             frame_id=frame_id)

    per_stream = 4 if SMOKE else 30
    probe_total = streams_n * per_stream
    window = 2 * micro
    start = time.perf_counter()
    probe_refs = []
    for index in range(min(window, probe_total)):
        submit_next(index)
    issued = min(window, probe_total)
    for _ in range(probe_total):
        _, _, outputs, status = gateway_responses.get(timeout=900)
        if status == "ok":
            probe_refs.append(outputs.get("detections"))
        if issued < probe_total:
            submit_next(issued)
            issued += 1
    capacity = probe_total / _honest_elapsed(start, probe_refs)
    for index in range(streams_n):
        gateway.post_message("destroy_stream", [f"g{index}"])

    # phase 3: base load, then the mid-run doubling -- only now does
    # the autoscaler watch (the probe's deliberate saturation must not
    # pre-trigger it).  Load is CLOSED-LOOP SESSION traffic: N
    # concurrent sessions, each keeping `window_per_session` frames in
    # flight (N users awaiting responses), and the doubling arrives as
    # N MORE sessions.  Sessions are bounded (`session_frames`) and
    # replaced on completion, so successors RE-PLACE on whatever pool
    # exists -- streams pin to a replica for their lifetime, and a load
    # swing made of immortal pinned streams could never use a grown
    # pool.  A session rejected at admission (typed `overloaded` while
    # every replica is saturated) is retried shortly after, like a real
    # client.
    gateway.enable_autoscale(_AUTOSCALE_POLICY, factory)
    window_per_session = micro
    session_frames = 10 * micro
    base_window = 1.5 if SMOKE else 3.0
    # the spike must outlive the warm bring-up: recovery is only
    # observable once the second replica is serving (and on a
    # shared-CPU smoke host, the bring-up itself steals cycles)
    spike_window = 8.0 if SMOKE else 10.0
    completions = []                      # perf_counter per ok frame
    counts = {"ok": 0, "shed": 0, "error": 0, "rejected_sessions": 0}
    ok_refs = []
    done = threading.Event()
    offering_done = threading.Event()
    lock = threading.Lock()
    sessions: dict = {}    # id -> {"cursor", "outstanding"}
    state = {"sequence": 0}

    def submit_one(stream_id, session):
        frame_id = session["cursor"]
        session["cursor"] += 1
        session["outstanding"] += 1
        gateway.submit_frame(stream_id,
                             {"image": images[frame_id % 4]},
                             frame_id=frame_id)

    def open_session():
        with lock:
            stream_id = f"sess{state['sequence']}"
            state["sequence"] += 1
            session = sessions[stream_id] = {"cursor": 0,
                                             "outstanding": 0}
        gateway.submit_stream(stream_id,
                              queue_response=gateway_responses)
        for _ in range(window_per_session):
            submit_one(stream_id, session)

    def drain():
        # the closed loop lives HERE: each ok/shed response funds the
        # session's next frame; an exhausted session is destroyed and
        # replaced (placement sees the CURRENT pool).  Timestamps are
        # engine-completion times (no per-frame device sync: on a
        # shared-CPU host a blocking sync in this thread becomes the
        # bottleneck); the final _honest_elapsed barrier keeps the
        # OVERALL number device-honest
        retry_at: list = []
        while True:
            now = time.perf_counter()
            while retry_at and retry_at[0] <= now:
                retry_at.pop(0)
                if not offering_done.is_set():
                    open_session()
            try:
                stream_id, frame_id, outputs, status = (
                    gateway_responses.get(
                        timeout=0.05 if retry_at else 2.0))
            except queue.Empty:
                if offering_done.is_set() and not any(
                        session["outstanding"]
                        for session in sessions.values()):
                    break
                continue
            if status == "overloaded":
                counts["rejected_sessions"] += 1
                sessions.pop(stream_id, None)
                retry_at.append(time.perf_counter() + 0.1)
                continue
            if status == "ok":
                completions.append(time.perf_counter())
                ok_refs.append(outputs.get("detections"))
                counts["ok"] += 1
            else:
                counts[status if status in counts else "error"] += 1
            session = sessions.get(stream_id)
            if session is None:
                continue
            session["outstanding"] -= 1
            if offering_done.is_set():
                continue
            if session["cursor"] < session_frames:
                submit_one(stream_id, session)
            elif session["outstanding"] <= 0:
                gateway.post_message("destroy_stream", [stream_id])
                sessions.pop(stream_id, None)
                open_session()
        done.set()

    pool_grew_at = []

    def watch_pool():
        while not done.is_set():
            if len(gateway.replicas) >= 2:
                pool_grew_at.append(time.perf_counter())
                return
            time.sleep(0.01)

    threading.Thread(target=watch_pool, daemon=True).start()
    start = time.perf_counter()
    for _ in range(streams_n):
        open_session()
    threading.Thread(target=drain, daemon=True).start()
    time.sleep(base_window)
    spike_started_at = time.perf_counter()
    for _ in range(streams_n):   # the doubling: N more sessions
        open_session()
    time.sleep(spike_window)
    offer_end = time.perf_counter()
    offering_done.set()
    done.wait(timeout=900)
    offered = counts["ok"] + counts["shed"] + counts["error"]
    elapsed = _honest_elapsed(start, ok_refs)

    def goodput_in(window_start, window_end):
        if window_end <= window_start:
            return None
        inside = sum(1 for moment in completions
                     if window_start <= moment <= window_end)
        return inside / (window_end - window_start)

    goodput_base = goodput_in(start, spike_started_at or offer_end)
    goodput_spike = goodput_in(spike_started_at or offer_end, offer_end)
    # the recovery window: from shortly after the pool actually grew
    # (the warm replica is serving and its bring-up no longer steals
    # host cycles) to the end of the offered spike; if the pool never
    # grew, fall back to the final quarter of the spike
    if pool_grew_at:
        recovery_start = min(pool_grew_at[0] + 1.0, offer_end)
    else:
        recovery_start = (spike_started_at or start) + 0.75 * (
            offer_end - (spike_started_at or start))
    goodput_recovered = goodput_in(recovery_start, offer_end)

    spawns = list(gateway.autoscaler.spawns)
    summary = gateway.telemetry.summary()
    scale_latency_s = (
        round(pool_grew_at[0] - spike_started_at, 3)
        if pool_grew_at and spike_started_at else None)
    # gateway teardown retires every factory-owned replica; replica0
    # was spawned directly (not autoscaler-owned), so it is ours
    gateway_process.terminate()
    handle0.process.terminate()
    disable_compile_cache()
    shutil.rmtree(cache_dir, ignore_errors=True)

    warm_spawn = next((spawn for spawn in spawns if spawn["warm"]),
                      spawns[0] if spawns else None)
    flops = detector_flops_per_image(config)
    return {
        "model": f"{preset} {size}x{size}",
        "policy": policy,
        "autoscale": _AUTOSCALE_POLICY,
        "topology": "in-process replicas, shared host",
        "capacity_single_fps": round(capacity, 1),
        "sessions_base": streams_n,
        "sessions_spike": 2 * streams_n,      # the mid-run doubling
        "window_per_session": window_per_session,
        "session_frames": session_frames,
        "responses": offered,
        "goodput_base_fps": (round(goodput_base, 1)
                             if goodput_base is not None else None),
        "goodput_spike_fps": (round(goodput_spike, 1)
                              if goodput_spike is not None else None),
        "goodput_recovered_fps": (round(goodput_recovered, 1)
                                  if goodput_recovered is not None
                                  else None),
        "recovered_vs_single_capacity": (
            round(goodput_recovered / max(capacity, 1e-9), 2)
            if goodput_recovered is not None else None),
        "completed": counts["ok"],
        "shed": counts["shed"],
        "rejected_sessions": counts["rejected_sessions"],
        "errors": counts["error"],
        "goodput_overall_fps": round(counts["ok"] / elapsed, 1),
        "scale_ups": summary["scale_ups"],
        "scale_latency_s": scale_latency_s,
        "time_to_healthy_cold_ms": round(time_to_healthy_cold_ms, 1),
        "cold_compiles": cold_info.get("cache_misses"),
        "spawns": spawns,
        "time_to_healthy_warm_ms": (warm_spawn["time_to_healthy_ms"]
                                    if warm_spawn else None),
        "warm_vs_cold_speedup": (
            round(time_to_healthy_cold_ms
                  / max(warm_spawn["time_to_healthy_ms"], 1e-9), 2)
            if warm_spawn else None),
        # the CI-asserted warm-start proof: zero recompiles of
        # fleet-known shapes during the warm replica's bring-up
        "compiles_in_window": (warm_spawn.get("cache_misses")
                               if warm_spawn else None),
        "mfu": _mfu((goodput_recovered or 0.0) * flops, peak),
    }


# -- chaos: the whole control plane under seeded process-level faults --------

# one spec, three surfaces: the HA gateway pair's journal, the
# definition parameter `aiko lint --bench` checks (AIKO407), and the
# published config block
_CHAOS_JOURNAL = "backend=retained;interval=0.02;search_timeout=0.5"


def _chaos_definition(name):
    """One deterministic integer element (x*3): the chaos scenario
    measures RECOVERY, not compute, and integer outputs make the
    bit-identical comparison exact by construction."""
    return {
        "name": name,
        "parameters": {"telemetry": TELEMETRY, "metrics_interval": 60.0,
                       "journal_policy": _CHAOS_JOURNAL},
        "graph": ["(multiply)"],
        "elements": [
            {"name": "multiply",
             "input": [{"name": "number", "type": "int"}],
             "output": [{"name": "number", "type": "int"}],
             "parameters": {"constant": 3},
             "deploy": {"local": {"module": ELEMENTS,
                                  "class_name": "PE_Multiply"}}},
        ],
    }


def bench_chaos(peak, seed: int | None = None):
    """`chaos` config: one seeded scenario kills the REGISTRAR primary,
    a REPLICA, and the GATEWAY primary mid-run under open client load,
    and proves the whole control plane recovers: the registrar
    secondary promotes and re-registers the fleet (round-8 LWT reap),
    the gateway migrates the dead replica's streams (PR-4 failover),
    and the HA standby adopts the retained journal and resumes every
    stream exactly-once (this round).  Two arms -- chaos and an
    uncrashed reference -- must produce BIT-IDENTICAL per-frame
    outputs with frames_lost == 0; published numbers are the
    time-to-recover per event, the standby takeover latency, and the
    registrar promote latency.  Runs entirely host-side (loopback
    broker, virtual processes): the number is a robustness bound, not
    a throughput figure."""
    import threading

    from aiko_services_tpu.faults import create_injector
    from aiko_services_tpu.pipeline import create_pipeline
    from aiko_services_tpu.pipeline.tensors import (
        decode_frame_data, encode_frame_data)
    from aiko_services_tpu.runtime import Process, Registrar
    from aiko_services_tpu.serve import Gateway
    from aiko_services_tpu.transport import reset_brokers
    from aiko_services_tpu.utils import generate, parse

    seed = int(os.environ.get("AIKO_CHAOS_SEED", "11")
               if seed is None else seed)
    streams_n = 4 if SMOKE else 8
    per_stream = 25 if SMOKE else 50
    total = streams_n * per_stream
    # the three kills land at seeded fractions of the submission run:
    # registrar first (so the replica kill is reaped by the PROMOTED
    # primary), then the replica, then the gateway
    kill_registrar = max(total // 4, 1)
    kill_replica = max(total // 2, 2)
    kill_gateway = max((3 * total) // 4, 3)
    group = "chaos"

    def wait(predicate, timeout=30.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if predicate():
                return True
            time.sleep(0.005)
        raise TimeoutError("chaos fleet condition not met")

    def run(chaos: bool):
        processes = []

        def make_process():
            process = Process(transport_kind="loopback")
            processes.append(process)
            return process

        registrar_1_process = make_process()
        registrar_1 = Registrar(registrar_1_process, name="reg1",
                                search_timeout=0.2)
        registrar_1_process.run(in_thread=True)
        wait(lambda: registrar_1.state == "primary")
        registrar_2_process = make_process()
        registrar_2 = Registrar(registrar_2_process, name="reg2",
                                search_timeout=0.2)
        registrar_2_process.run(in_thread=True)
        wait(lambda: registrar_2.state == "secondary")
        replicas = []
        for index in range(2):
            process = make_process()
            replicas.append((process, create_pipeline(
                process, _chaos_definition(f"chaos_replica{index}"))))
            process.run(in_thread=True)

        def make_gateway():
            process = make_process()
            gateway = Gateway(process, policy="max_inflight=16;queue=256",
                              router_seed=seed, journal=_CHAOS_JOURNAL,
                              ha=group, metrics_interval=60.0)
            gateway.discover(name="chaos_replica*")
            process.run(in_thread=True)
            return gateway

        gateway_a = make_gateway()
        wait(lambda: gateway_a.role == "primary")
        gateway_b = make_gateway()
        wait(lambda: gateway_b.election.state == "secondary")
        for gateway in (gateway_a, gateway_b):
            wait(lambda: len(gateway.replicas) == 2 and all(
                replica.consumer.last_update is not None
                for replica in gateway.replicas.values()))

        client_process = make_process()
        reply_topic = (f"{client_process.topic_path_process}/0/"
                       f"chaos_client")
        lock = threading.Lock()
        responses: dict = {}
        response_times: list = []
        primary = {"topic": gateway_a.topic_path}

        def on_reply(topic, payload):
            try:
                command, parameters = parse(payload)
            except ValueError:
                return
            if command != "process_frame_response" or not parameters:
                return
            reply = parameters[0]
            if not isinstance(reply, dict) or reply.get("event"):
                return
            key = (str(reply.get("stream_id")),
                   int(reply.get("frame_id", -1)))
            outputs = (decode_frame_data(parameters[1])
                       if len(parameters) > 1 else {})
            now = time.perf_counter()
            with lock:
                if key not in responses:
                    responses[key] = outputs.get("number")
                    response_times.append((now, key))

        def on_boot(topic, payload):
            try:
                command, parameters = parse(payload)
            except ValueError:
                return
            if (command == "primary" and parameters
                    and parameters[0] == "found" and len(parameters) > 1):
                primary["topic"] = str(parameters[1])

        client_process.add_message_handler(on_reply, reply_topic)
        client_process.add_message_handler(
            on_boot, f"{client_process.namespace}/gateway/{group}")
        client_process.run(in_thread=True)
        stream_ids = [f"c{index}" for index in range(streams_n)]

        def create(stream_id):
            client_process.publish(
                f"{primary['topic']}/in",
                generate("create_stream", [
                    stream_id, json.dumps({}).encode("ascii"), 600.0,
                    reply_topic]))

        def submit(stream_id, frame_id):
            client_process.publish(
                f"{primary['topic']}/in",
                generate("process_frame", [
                    {"stream_id": stream_id, "frame_id": frame_id},
                    encode_frame_data(
                        {"number": frame_id}).encode("ascii")]))

        injector = create_injector(
            f"seed={seed};"
            f"registrar_kill:node=reg1:frame={kill_registrar};"
            f"process_kill:node=replica0:frame={kill_replica};"
            f"process_kill:node=gateway_a:frame={kill_gateway}"
        ) if chaos else None
        events: list = []
        start = time.perf_counter()

        def chaos_tick():
            """One seeded consult per submission per point -- the
            deterministic chaos plan (faults.py process-scoped points,
            exercised through Process.crash / transport sever)."""
            if injector is None:
                return
            now = round(time.perf_counter() - start, 3)
            if injector.registrar_kill("reg1"):
                registrar_1_process.crash()
                event = {"type": "registrar_kill", "target": "reg1",
                         "at_s": now}
                events.append(event)

                def note_promote(event=event):
                    t0 = time.perf_counter()
                    while (registrar_2.state != "primary"
                           and time.perf_counter() - t0 < 30):
                        time.sleep(0.002)
                    event["promote_ms"] = round(
                        (time.perf_counter() - t0) * 1000, 1)

                threading.Thread(target=note_promote,
                                 daemon=True).start()
            if injector.process_kill("replica0"):
                replicas[0][0].crash()
                events.append({"type": "replica_kill",
                               "target": "chaos_replica0", "at_s": now})
            if injector.process_kill("gateway_a"):
                gateway_a.process.crash()
                events.append({"type": "gateway_kill",
                               "target": "gateway_a", "at_s": now})

        try:
            for stream_id in stream_ids:
                create(stream_id)
            cursors = {stream_id: 0 for stream_id in stream_ids}
            for index in range(total):
                stream_id = stream_ids[index % streams_n]
                frame_id = cursors[stream_id]
                cursors[stream_id] += 1
                submit(stream_id, frame_id)
                chaos_tick()
                time.sleep(0.002)
            # drain: the client replays un-acked frames against the
            # CURRENT primary (the retained announce) until every
            # frame is answered -- the exactly-once dedupe makes the
            # replay idempotent
            expected = {(stream_id, frame_id)
                        for stream_id in stream_ids
                        for frame_id in range(per_stream)}
            deadline = time.monotonic() + (60 if SMOKE else 120)
            resubmit_rounds = 0
            while time.monotonic() < deadline:
                with lock:
                    missing = expected - set(responses)
                if not missing:
                    break
                resubmit_rounds += 1
                for stream_id in {key[0] for key in missing}:
                    create(stream_id)   # idempotent re-assertion
                for stream_id, frame_id in sorted(missing):
                    submit(stream_id, frame_id)
                time.sleep(0.4)
            with lock:
                got = dict(responses)
                times = list(response_times)
            for event in events:
                after = [t for t, _ in times
                         if t - start > event["at_s"]]
                event["ttr_ms"] = (round(
                    (min(after) - start - event["at_s"]) * 1000, 1)
                    if after else None)
            summary = (gateway_b if chaos
                       else gateway_a).telemetry.summary()
            return {
                "outputs": got,
                "events": events,
                "frames_lost": len(expected) - len(got),
                "resubmit_rounds": resubmit_rounds,
                "takeover_ms": (gateway_b.telemetry.last_takeover_ms
                                if chaos else None),
                "injected": injector.stats() if injector else {},
                "ha": summary.get("ha", {}),
            }
        finally:
            for process in processes:
                try:
                    process.terminate()
                except Exception:
                    pass

    reference = run(chaos=False)
    reset_brokers()
    chaotic = run(chaos=True)
    reset_brokers()
    bit_identical = chaotic["outputs"] == reference["outputs"]
    result = {
        "seed": seed,
        "streams": streams_n,
        "frames_total": total,
        "frames_lost": chaotic["frames_lost"],
        "frames_lost_reference": reference["frames_lost"],
        "bit_identical_to_uncrashed": bit_identical,
        "events": chaotic["events"],
        "takeover_ms": chaotic["takeover_ms"],
        "registrar_promote_ms": next(
            (event.get("promote_ms") for event in chaotic["events"]
             if event["type"] == "registrar_kill"), None),
        "resubmit_rounds": chaotic["resubmit_rounds"],
        "injected": chaotic["injected"],
        "journal": chaotic["ha"],
        "topology": ("registrar pair + 2 wire-discovered replicas + "
                     "HA gateway pair, loopback broker"),
    }
    result["decode_replica_kill"] = _chaos_decode_replica_kill(seed)
    result["region_partition"] = _chaos_region_partition(seed)
    timeline_path = os.environ.get("AIKO_CHAOS_TIMELINE")
    if timeline_path:
        try:
            with open(timeline_path, "w") as handle:
                json.dump({key: value for key, value in result.items()
                           if key != "outputs"}, handle, indent=2)
            result["timeline_file"] = timeline_path
        except OSError as error:
            result["timeline_error"] = str(error)
    return result


def _chaos_decode_definition(name, max_new=24, slots=6,
                             keeper="bench_ckpt_keeper"):
    """One checkpointed continuous decode replica (warm KV failover):
    the `decode_replica_kill` scenario's definition, also collected
    into the `aiko lint --bench` surface so its AIKO405/408/409
    parameter set stays strict-mode clean."""
    return {
        "name": name,
        "parameters": {"telemetry": TELEMETRY,
                       "metrics_interval": 60.0},
        "graph": ["(lm)"],
        "elements": [
            {"name": "lm",
             "input": [{"name": "tokens", "type": "any"},
                       {"name": "restore", "type": "any",
                        "optional": True}],
             "output": [{"name": "generated", "type": "any"}],
             "parameters": {
                 "vocab_size": 300, "d_model": 32, "n_layers": 1,
                 "n_heads": 2, "n_kv_heads": 1, "d_ff": 64,
                 "max_seq_len": 128, "dtype": "float32",
                 "max_new_tokens": max_new, "continuous": True,
                 "decode_slots": slots, "kv_block_size": 8,
                 "stream_tokens": True, "stream_chunk": 1,
                 "checkpoint": (f"checkpoint_every=1;"
                                f"max_checkpoint_lag=4;"
                                f"keeper={keeper}")},
             "deploy": {"local": {"module": ELEMENTS,
                                  "class_name": "LMGenerate"}}},
        ],
    }


def _chaos_decode_replica_kill(seed: int):
    """Warm KV failover under a continuous-batching storm: a gateway
    fronts two checkpointed decode replicas, a seeded plan kills one
    MID-DECODE, and the paced failover replays every migrated stream
    with a restore hint -- the survivor adopts each stream's
    checkpointed KV (decode/checkpoint.py) and re-decodes at most
    `max_checkpoint_lag` tokens instead of re-prefilling the prompt.
    Two arms (kill vs uncrashed) must be BIT-IDENTICAL with
    frames_lost == 0 and ZERO survivor recompiles in the measured
    window; the published numbers are the reprefill-avoided fraction
    and the recovery TTFT (kill -> first post-kill token per migrated
    stream)."""
    import threading

    from aiko_services_tpu.decode import CheckpointKeeper, reset_keepers
    from aiko_services_tpu.faults import create_injector
    from aiko_services_tpu.pipeline import create_pipeline
    from aiko_services_tpu.runtime import Process
    from aiko_services_tpu.serve import Gateway
    from aiko_services_tpu.transport import reset_brokers
    from aiko_services_tpu.utils import parse

    import numpy as np

    streams_n = 6 if SMOKE else 12
    max_new = 24 if SMOKE else 48
    prompt_len = 6
    keeper_name = "bench_ckpt_keeper"
    checkpoint_spec = (f"checkpoint_every=1;max_checkpoint_lag=4;"
                       f"keeper={keeper_name}")
    rng = np.random.default_rng(seed)
    frames = [rng.integers(1, 300, size=(1, prompt_len))
              .astype(np.int32) for _ in range(streams_n)]

    def lm_definition(name):
        return _chaos_decode_definition(name, max_new=max_new,
                                        slots=streams_n,
                                        keeper=keeper_name)

    def wait(predicate, timeout=60.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if predicate():
                return True
            time.sleep(0.005)
        raise TimeoutError("decode_replica_kill condition not met")

    def run(kill: bool):
        reset_keepers()
        keeper = CheckpointKeeper(keeper_name)
        processes = []

        def make_process():
            process = Process(transport_kind="loopback")
            processes.append(process)
            return process

        replica_a = create_pipeline(make_process(),
                                    lm_definition("ck_dec0"))
        replica_b = create_pipeline(make_process(),
                                    lm_definition("ck_dec1"))
        gateway_process = make_process()
        gateway = Gateway(
            gateway_process, policy="max_inflight=32;queue=128",
            router_seed=seed, metrics_interval=60.0,
            checkpoint=f"recovery_rate=4;keeper={keeper_name}")
        # all streams pin to replica A; B joins as the warm standby
        # right before the kill, so the failover wave lands on it
        gateway.attach_replica(replica_a)
        lock = threading.Lock()
        token_times: dict = {}    # (stream, offset) -> first-seen time

        def on_out(topic, payload):
            try:
                command, parameters = parse(payload)
            except ValueError:
                return
            if command != "token_chunk" or len(parameters) < 5:
                return
            now = time.perf_counter()
            stream_id = str(parameters[0])
            offset = int(parameters[3])
            with lock:
                for j in range(len(parameters[4][0])):
                    token_times.setdefault((stream_id, offset + j),
                                           now)

        for pipe in (replica_a, replica_b):
            pipe.process.add_message_handler(
                on_out, f"{pipe.elements['lm'].topic_path}/out")
        for process in processes:
            process.run(in_thread=True)

        # warm BOTH engines (the one prompt bucket + the decode step)
        # before the measured window, so the survivor's recompile
        # count during recovery is attributable to recovery alone
        responses = queue.Queue()
        for index, (name, pipe) in enumerate(
                (("warm_a", replica_a), ("warm_b", replica_b))):
            stream = pipe.create_stream(f"{name}", grace_time=300,
                                        queue_response=responses)
            pipe.create_frame(stream, {"tokens": frames[0]})
            responses.get(timeout=120)
            pipe.destroy_stream(f"{name}")
        warm_compiles = {
            "a": replica_a.elements["lm"].engine_stats()["compiles"],
            "b": replica_b.elements["lm"].engine_stats()["compiles"]}

        # frame=0: the kill fires on the plan's FIRST consult for this
        # node (the harness consults once, at the seeded mid-storm
        # point: every stream checkpointed, none finished)
        injector = create_injector(
            f"seed={seed};process_kill:node=ck_dec0:frame=0"
        ) if kill else None
        results = queue.Queue()
        for index, frame in enumerate(frames):
            gateway.submit_stream(f"s{index}", {},
                                  queue_response=results)
            gateway.submit_frame(f"s{index}", {"tokens": frame},
                                 frame_id=0)
        kill_at = None
        migrated = []
        if kill:
            # mid-storm: every stream checkpointed, none finished
            wait(lambda: keeper.flush(timeout=0.1)
                 and keeper.kept_count() >= streams_n)
            gateway.attach_replica(replica_b)
            if injector.process_kill("ck_dec0"):
                migrated = sorted(
                    gateway.replicas[replica_a.topic_path].streams)
                kill_at = time.perf_counter()
                # a REAL death: sever + halt with no clean shutdown
                # (Process.crash), so replica A emits nothing after
                # kill_at and the recovery metrics measure the
                # survivor's restores, not the victim's death throes
                replica_a.process.crash()
                gateway.post_message("_replica_lost", [
                    replica_a.topic_path, "injected decode_replica_kill"])
        outputs = {}
        deadline = time.monotonic() + (120 if SMOKE else 300)
        while len(outputs) < streams_n:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                stream_id, _frame_id, out, status = results.get(
                    timeout=remaining)
            except queue.Empty:
                break
            if status == "ok":
                outputs[stream_id] = np.asarray(
                    out["generated"]).tolist()
        survivor = replica_b.elements["lm"]
        engine = survivor.engine_stats() or {}
        recovery_ttft_ms = []
        if kill_at is not None:
            with lock:
                times = dict(token_times)
            for stream_id in migrated:
                post = [t for (s, _o), t in times.items()
                        if s == stream_id and t > kill_at]
                if post:
                    recovery_ttft_ms.append(
                        (min(post) - kill_at) * 1000.0)
        summary = gateway.telemetry.summary()
        block = {
            "outputs": outputs,
            "frames_lost": streams_n - len(outputs),
            "migrated_streams": len(migrated),
            "restores": engine.get("restores", 0),
            "restore_fallbacks": engine.get("restore_fallbacks", 0),
            "restore_replayed_tokens": engine.get(
                "restore_replayed_tokens", 0),
            "recovery_paced": summary.get("recovery_paced", 0),
            "compiles_in_window": (
                (replica_b.elements["lm"].engine_stats()["compiles"]
                 - warm_compiles["b"]) if kill else 0),
            "checkpoints": (survivor.checkpoint_stats()
                            or {}).get("checkpoints", 0),
            "keeper": keeper.stats(),
            "recovery_ttft_ms": sorted(recovery_ttft_ms),
        }
        for process in processes:
            try:
                process.terminate()
            except Exception:
                pass
        reset_keepers()
        reset_brokers()
        return block

    reference = run(kill=False)
    chaotic = run(kill=True)
    restores = chaotic["restores"]
    fallbacks = chaotic["restore_fallbacks"]
    ttft = chaotic["recovery_ttft_ms"]
    block = {
        "seed": seed,
        "streams": streams_n,
        "max_new_tokens": max_new,
        "checkpoint_spec": checkpoint_spec,
        "frames_lost": chaotic["frames_lost"],
        "frames_lost_reference": reference["frames_lost"],
        "bit_identical": chaotic["outputs"] == reference["outputs"],
        "migrated_streams": chaotic["migrated_streams"],
        "restores": restores,
        "restore_fallbacks": fallbacks,
        # the headline: migrated streams resumed from checkpoints
        # instead of re-running their (compute-bound) prompt prefill
        "reprefill_avoided_frac": round(
            restores / max(restores + fallbacks, 1), 4),
        "restore_replayed_tokens": chaotic["restore_replayed_tokens"],
        "recovery_paced": chaotic["recovery_paced"],
        "compiles_in_window": chaotic["compiles_in_window"],
        "keeper": chaotic["keeper"],
        "recovery_ttft_p50_ms": (round(ttft[len(ttft) // 2], 2)
                                 if ttft else None),
        "recovery_ttft_p99_ms": (round(ttft[min(
            int(len(ttft) * 0.99), len(ttft) - 1)], 2)
            if ttft else None),
        "topology": ("2 checkpointed continuous decode replicas + "
                     "standby keeper + paced gateway, loopback"),
    }
    return block


def _chaos_region_partition(seed: int):
    """Region loss under a continuous-batching storm: a two-region
    federated tier (`groups=us:a,eu:c`, one checkpointed decode
    replica per region, a SHARED CheckpointKeeper) loses the eu
    region at a seeded `region_partition` point mid-storm.  The
    surviving us gateway warms the lost group's journal mirror,
    adopts exactly its rendezvous share of the eu streams
    (region-aware owner_of over the survivors), and the client's
    resubmitted frames carry the one-shot warm-restore hint -- the us
    decode replica restores each adopted stream's checkpointed KV and
    re-decodes only the post-snapshot tail instead of cold
    re-prefilling.  Both arms (partition vs lossless) must be
    BIT-IDENTICAL with frames_lost == 0 and reprefill_avoided_frac >
    0: journal failover (round 13) x warm checkpoints (round 17) x
    federation (round 19) composed into one robustness proof."""
    import threading

    from aiko_services_tpu.decode import CheckpointKeeper, reset_keepers
    from aiko_services_tpu.faults import create_injector
    from aiko_services_tpu.pipeline import create_pipeline
    from aiko_services_tpu.pipeline.tensors import encode_frame_data
    from aiko_services_tpu.runtime import Process
    from aiko_services_tpu.serve import FederationRouter, Gateway
    from aiko_services_tpu.transport import reset_brokers
    from aiko_services_tpu.utils import generate, parse

    import numpy as np

    streams_n = 6 if SMOKE else 12
    max_new = 24 if SMOKE else 48
    prompt_len = 6
    keeper_name = "bench_region_keeper"
    federation_groups = "groups=us:a,eu:c"
    rng = np.random.default_rng(seed + 1)
    frames = [rng.integers(1, 300, size=(1, prompt_len))
              .astype(np.int32) for _ in range(streams_n)]
    # alternate regions so BOTH gateways carry streams and the
    # partition remaps exactly the eu half
    regions = {f"r{index}": ("us" if index % 2 == 0 else "eu")
               for index in range(streams_n)}
    eu_ids = sorted(sid for sid, region in regions.items()
                    if region == "eu")

    def wait(predicate, timeout=60.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if predicate():
                return True
            time.sleep(0.005)
        raise TimeoutError("region_partition condition not met")

    def run(partition: bool):
        reset_keepers()
        keeper = CheckpointKeeper(keeper_name)
        processes = []

        def make_process():
            process = Process(transport_kind="loopback")
            processes.append(process)
            return process

        replicas = {
            "us": create_pipeline(
                make_process(), _chaos_decode_definition(
                    "rg_dec_us", max_new=max_new, slots=streams_n,
                    keeper=keeper_name)),
            "eu": create_pipeline(
                make_process(), _chaos_decode_definition(
                    "rg_dec_eu", max_new=max_new, slots=streams_n,
                    keeper=keeper_name)),
        }
        gateways = {}
        for group, region in (("a", "us"), ("c", "eu")):
            gateways[group] = Gateway(
                make_process(), name=group,
                policy="max_inflight=32;queue=128",
                router_seed=seed, metrics_interval=60.0,
                journal=_CHAOS_JOURNAL,
                federation=(f"{federation_groups};"
                            f"group={region}:{group}"),
                checkpoint=f"recovery_rate=4;keeper={keeper_name}")
            gateways[group].attach_replica(replicas[region])
        router = FederationRouter(gateways, policy=federation_groups)

        client_process = make_process()
        reply_topic = (f"{client_process.topic_path_process}/0/"
                       f"region_client")
        lock = threading.Lock()
        outputs: dict = {}

        def on_reply(topic, payload):
            try:
                command, parameters = parse(payload)
            except ValueError:
                return
            if (command != "process_frame_response"
                    or len(parameters) < 2):
                return
            reply = parameters[0]
            if not isinstance(reply, dict) or reply.get("event"):
                return
            from aiko_services_tpu.pipeline.tensors import (
                decode_frame_data)
            generated = decode_frame_data(parameters[1]).get(
                "generated")
            with lock:
                outputs.setdefault(
                    str(reply.get("stream_id")),
                    np.asarray(generated).tolist())

        client_process.add_message_handler(on_reply, reply_topic)
        for process in processes:
            process.run(in_thread=True)

        def create(stream_id):
            group = router.group_for(stream_id,
                                     region=regions[stream_id])
            client_process.publish(
                f"{gateways[group].topic_path}/in",
                generate("create_stream", [
                    stream_id,
                    json.dumps({"region": regions[stream_id]})
                    .encode("ascii"),
                    600.0, reply_topic]))

        def submit(stream_id):
            group = router.group_for(stream_id,
                                     region=regions[stream_id])
            client_process.publish(
                f"{gateways[group].topic_path}/in",
                generate("process_frame", [
                    {"stream_id": stream_id, "frame_id": 0},
                    encode_frame_data(
                        {"tokens": frames[int(stream_id[1:])]})
                    .encode("ascii")]))

        injector = create_injector(
            f"seed={seed};region_partition:node=eu:frame=0"
        ) if partition else None
        partition_at = None
        for stream_id in sorted(regions):
            create(stream_id)
            submit(stream_id)
        if partition:
            # mid-storm: every stream checkpointed, none finished,
            # and the eu group's journal holds its streams' pins
            wait(lambda: keeper.flush(timeout=0.1)
                 and keeper.kept_count() >= streams_n)
            wait(lambda: gateways["c"].journal.entry_count()
                 >= len(eu_ids))
            if injector.region_partition("eu", frame_id=0,
                                         scope="bench") != 0.0:
                partition_at = time.perf_counter()
                # the WHOLE region goes dark at once: replica and
                # gateway sever with no clean shutdown
                replicas["eu"].process.crash()
                gateways["c"].process.crash()
                router.fail_group("c")
            # adoption before resubmission: the us gateway must hold
            # the eu streams (restore hints armed) before the client's
            # replay lands, or a fresh create would cold-prefill
            wait(lambda: gateways["a"].telemetry
                 .region_migrations.value >= len(eu_ids),
                 timeout=60 if SMOKE else 120)
        deadline = time.monotonic() + (120 if SMOKE else 300)
        while time.monotonic() < deadline:
            with lock:
                missing = sorted(set(regions) - set(outputs))
            if not missing:
                break
            if partition_at is not None:
                # client replay against the surviving region: the
                # create is an idempotent re-assertion, the frame
                # dedupes against the restored floor
                for stream_id in missing:
                    create(stream_id)
                    submit(stream_id)
            time.sleep(0.4)
        with lock:
            got = dict(outputs)
        recovery_ms = None
        if partition_at is not None:
            recovery_ms = round(
                (time.perf_counter() - partition_at) * 1000, 1)
        survivor = replicas["us"].elements["lm"]
        engine = survivor.engine_stats() or {}
        summary = gateways["a"].telemetry.summary()
        block = {
            "outputs": got,
            "frames_lost": streams_n - len(got),
            "region_migrations": summary.get("region_migrations", 0),
            "region_affinity_hits": summary.get(
                "region_affinity_hits", 0),
            "region_affinity_misses": summary.get(
                "region_affinity_misses", 0),
            "restores": engine.get("restores", 0),
            "restore_fallbacks": engine.get("restore_fallbacks", 0),
            "injected": injector.stats() if injector else {},
            "recovery_ms": recovery_ms,
        }
        for process in processes:
            try:
                process.terminate()
            except Exception:
                pass
        reset_keepers()
        reset_brokers()
        return block

    reference = run(partition=False)
    partitioned = run(partition=True)
    restores = partitioned["restores"]
    fallbacks = partitioned["restore_fallbacks"]
    return {
        "seed": seed,
        "streams": streams_n,
        "regions": {"us": streams_n - len(eu_ids),
                    "eu": len(eu_ids)},
        "frames_lost": partitioned["frames_lost"],
        "frames_lost_reference": reference["frames_lost"],
        "bit_identical": partitioned["outputs"] == reference["outputs"],
        "region_migrations": partitioned["region_migrations"],
        "region_affinity_hits": partitioned["region_affinity_hits"],
        "region_affinity_misses": partitioned[
            "region_affinity_misses"],
        "restores": restores,
        "restore_fallbacks": fallbacks,
        # the headline: adopted streams resumed from the shared
        # keeper's checkpoints instead of re-running prompt prefill
        "reprefill_avoided_frac": round(
            restores / max(restores + fallbacks, 1), 4),
        "recovery_ms": partitioned["recovery_ms"],
        "injected": partitioned["injected"],
        "topology": ("two-region federated tier (us:a, eu:c), one "
                     "checkpointed decode replica per region, shared "
                     "keeper, journaled gateways, loopback"),
    }


# -- autopilot: the online SLO control loop (observe -> decide -> act) -------

# one spec, three surfaces: the gateway policy the bench arms run, the
# definition parameter `aiko lint --bench` checks (AIKO412), and the
# published config block.  interval=0: the bench drives ticks itself
# (tick_now / posted collects) instead of arming the wire timer, so
# every run is deterministic
_AUTOPILOT_POLICY = ("interval=0;apply=on;max_delta_frac=0.5;"
                     "margin=0.15;burn_threshold=0.02")
# the deliberately mis-tuned cold default the loop must walk back from,
# and the value an operator hand-tunes for a closed-loop window of 2
# (the recommender's fixed point: pow2 of the observed group occupancy)
_AUTOPILOT_COLD_MICRO = 16
_AUTOPILOT_TUNED_MICRO = 2


def _autopilot_definition(name, micro=_AUTOPILOT_COLD_MICRO,
                          work_ms=2):
    """One fixed-host-cost element (PE_Busy) behind the gateway: the
    autopilot scenario measures the CONTROL LOOP, not compute, and the
    work_ms floor makes the queue-bound classification (starved
    micro_batch groups) deterministic on any host.  Telemetry is
    FORCED on: the trace harvest is the loop's input."""
    return {
        "name": name,
        "parameters": {"telemetry": True, "metrics_interval": 60.0,
                       "autopilot_policy": _AUTOPILOT_POLICY},
        "graph": ["(busy)"],
        "elements": [
            # "any": the chaos arm feeds exact ints (bit-identical by
            # construction), the convergence arm feeds f32 arrays (only
            # array inputs coalesce under micro-batching)
            {"name": "busy",
             "input": [{"name": "number", "type": "any"}],
             "output": [{"name": "number", "type": "any"}],
             "parameters": {"micro_batch": micro,
                            "micro_batch_wait_ms": 4,
                            "work_ms": work_ms, "constant": 3},
             "deploy": {"local": {"module": ELEMENTS,
                                  "class_name": "PE_Busy"}}},
        ],
    }


def _autopilot_replica_compiles(pipeline) -> int:
    """Sum of every `pipeline.compiles_*` counter on one replica: the
    no-recompile proof reads the delta across the apply window."""
    registry = pipeline.telemetry.registry
    return sum(counter.value
               for name, counter in registry._counters.items()
               if name.startswith("pipeline.compiles_"))


def _autopilot_convergence_arm():
    """Cold mis-tuned fleet -> deterministic tick_now() loop -> the
    applied configuration must land within `margin` of the hand-tuned
    settings, with every delta clamped/journal-accounted in the
    per-tick ledger and ZERO replica recompiles in the apply window."""
    import numpy as np

    from aiko_services_tpu.pipeline import create_pipeline
    from aiko_services_tpu.runtime import Process
    from aiko_services_tpu.serve import Gateway
    from aiko_services_tpu.transport import reset_brokers

    total = 40 if SMOKE else 120
    max_ticks = 12

    def run_load(gateway, responses, start_frame, count):
        """Closed-loop window-2 session traffic: the arrival pattern
        that starves a micro_batch=16 group (median occupancy ~2).
        Frames carry small float arrays -- ONLY array inputs coalesce
        under micro-batching, and the starved-group queue wait IS the
        signal the loop tunes on."""
        submitted, done = 0, 0
        start = time.perf_counter()

        def push():
            nonlocal submitted
            gateway.submit_frame(
                "s0",
                {"number": np.full((1, 2), float(submitted),
                                   np.float32)},
                frame_id=start_frame + submitted)
            submitted += 1

        while submitted < min(2, count):
            push()
        outputs = {}
        while done < count:
            _, frame_id, out, status = responses.get(timeout=120)
            done += 1
            if status == "ok":
                outputs[int(frame_id)] = float(
                    np.asarray(out.get("number")).ravel()[0])
            if submitted < count:
                push()
        return count / max(time.perf_counter() - start, 1e-9), outputs

    def fleet(micro, autopilot):
        process = Process(transport_kind="loopback")
        pipeline = create_pipeline(
            process, _autopilot_definition("bench_autopilot",
                                           micro=micro))
        gateway_process = Process(transport_kind="loopback")
        gateway = Gateway(gateway_process,
                          policy="max_inflight=64;queue=256",
                          router_seed=7, telemetry=True,
                          metrics_interval=60.0, autopilot=autopilot)
        gateway.attach_replica(pipeline)
        process.run(in_thread=True)
        gateway_process.run(in_thread=True)
        responses = queue.Queue()
        gateway.submit_stream("s0", queue_response=responses)
        return process, pipeline, gateway_process, gateway, responses

    # arm 1: cold (mis-tuned micro_batch) + the live control loop
    process, pipeline, gateway_process, gateway, responses = fleet(
        _AUTOPILOT_COLD_MICRO, _AUTOPILOT_POLICY)
    goodput_cold, cold_outputs = run_load(gateway, responses, 0, total)
    compiles_before = _autopilot_replica_compiles(pipeline)
    pilot = gateway.autopilot
    ticks = 0
    for _ in range(max_ticks):
        pilot.tick_now()
        ticks += 1
        tick = pilot.ledger[-1] if pilot.ledger else {}
        if tick.get("converged") and not tick.get("applied"):
            break
    compiles_in_window = (_autopilot_replica_compiles(pipeline)
                          - compiles_before)
    goodput_converged, converged_outputs = run_load(
        gateway, responses, total, total)
    micro_converged = pipeline.elements["busy"].get_parameter(
        "micro_batch")
    summary = pilot.summary()
    ledger = [dict(tick) for tick in pilot.ledger]
    gateway_process.terminate()
    process.terminate()
    reset_brokers()

    # arm 2: the hand-tuned reference, no autopilot
    process, pipeline, gateway_process, gateway, responses = fleet(
        _AUTOPILOT_TUNED_MICRO, None)
    goodput_tuned, tuned_outputs = run_load(gateway, responses, 0,
                                            total)
    gateway_process.terminate()
    process.terminate()
    reset_brokers()

    return {
        "frames_per_arm": total,
        "micro_cold": _AUTOPILOT_COLD_MICRO,
        "micro_hand_tuned": _AUTOPILOT_TUNED_MICRO,
        "micro_converged": (int(micro_converged)
                            if micro_converged is not None else None),
        "ticks": ticks,
        "converged": summary.get("converged", False),
        "convergence": summary.get("convergence"),
        "margin": pilot.policy.margin,
        "deltas_applied": summary.get("deltas_applied", 0),
        "deltas_clamped": summary.get("deltas_clamped", 0),
        "deltas_skipped": summary.get("deltas_skipped", 0),
        "compiles_in_window": compiles_in_window,
        "goodput_cold_fps": round(goodput_cold, 1),
        "goodput_converged_fps": round(goodput_converged, 1),
        "goodput_hand_tuned_fps": round(goodput_tuned, 1),
        "converged_vs_hand_tuned": round(
            goodput_converged / max(goodput_tuned, 1e-9), 2),
        # outputs are micro_batch-invariant by construction: retuning
        # mid-fleet must never change WHAT is computed
        "outputs_invariant": (
            set(cold_outputs.values()) == set(tuned_outputs.values())
            == set(converged_outputs.values())),
        "ledger": ledger,
    }


def _autopilot_chaos_arm(seed: int):
    """Seeded `process_kill` of the HA gateway primary in the apply
    window: the standby promotes, adopts the retained delta journal
    (every applied delta accounted, none re-applied), and the run's
    per-frame outputs stay BIT-IDENTICAL to an unkilled reference with
    frames_lost == 0."""
    import threading

    from aiko_services_tpu.faults import create_injector
    from aiko_services_tpu.pipeline import create_pipeline
    from aiko_services_tpu.pipeline.tensors import (
        decode_frame_data, encode_frame_data)
    from aiko_services_tpu.runtime import Process, Registrar
    from aiko_services_tpu.serve import Gateway
    from aiko_services_tpu.transport import reset_brokers
    from aiko_services_tpu.utils import generate, parse

    streams_n = 2 if SMOKE else 4
    per_stream = 20 if SMOKE else 40
    total = streams_n * per_stream
    # first autopilot tick ~40%, kill in the apply window at ~70%
    tick_frames = {max(2 * total // 5, 1), max(11 * total // 20, 2)}
    kill_gateway = max(7 * total // 10, 3)
    group = "autopilot_chaos"

    def wait(predicate, timeout=30.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if predicate():
                return True
            time.sleep(0.005)
        raise TimeoutError("autopilot chaos fleet condition not met")

    def run(chaos: bool):
        processes = []

        def make_process():
            process = Process(transport_kind="loopback")
            processes.append(process)
            return process

        registrar_process = make_process()
        registrar = Registrar(registrar_process, name="reg",
                              search_timeout=0.2)
        registrar_process.run(in_thread=True)
        wait(lambda: registrar.state == "primary")
        replica_process = make_process()
        replica = create_pipeline(
            replica_process,
            _autopilot_definition("autopilot_replica", work_ms=1))
        replica_process.run(in_thread=True)

        def make_gateway():
            process = make_process()
            gateway = Gateway(
                process, policy="max_inflight=32;queue=512",
                router_seed=seed, journal=_CHAOS_JOURNAL, ha=group,
                autopilot=_AUTOPILOT_POLICY, metrics_interval=60.0)
            gateway.discover(name="autopilot_replica*")
            process.run(in_thread=True)
            return gateway

        gateway_a = make_gateway()
        wait(lambda: gateway_a.role == "primary")
        gateway_b = make_gateway()
        wait(lambda: gateway_b.election.state == "secondary")
        for gateway in (gateway_a, gateway_b):
            wait(lambda: len(gateway.replicas) == 1 and all(
                handle.consumer.last_update is not None
                for handle in gateway.replicas.values()))

        client_process = make_process()
        reply_topic = (f"{client_process.topic_path_process}/0/"
                       f"autopilot_chaos")
        lock = threading.Lock()
        responses: dict = {}
        primary = {"topic": gateway_a.topic_path}

        def on_reply(topic, payload):
            try:
                command, parameters = parse(payload)
            except ValueError:
                return
            if command != "process_frame_response" or not parameters:
                return
            reply = parameters[0]
            if not isinstance(reply, dict) or reply.get("event"):
                return
            key = (str(reply.get("stream_id")),
                   int(reply.get("frame_id", -1)))
            outputs = (decode_frame_data(parameters[1])
                       if len(parameters) > 1 else {})
            with lock:
                responses.setdefault(key, outputs.get("number"))

        def on_boot(topic, payload):
            try:
                command, parameters = parse(payload)
            except ValueError:
                return
            if (command == "primary" and parameters
                    and parameters[0] == "found"
                    and len(parameters) > 1):
                primary["topic"] = str(parameters[1])

        client_process.add_message_handler(on_reply, reply_topic)
        client_process.add_message_handler(
            on_boot, f"{client_process.namespace}/gateway/{group}")
        client_process.run(in_thread=True)
        stream_ids = [f"c{index}" for index in range(streams_n)]

        def create(stream_id):
            client_process.publish(
                f"{primary['topic']}/in",
                generate("create_stream", [
                    stream_id, json.dumps({}).encode("ascii"), 600.0,
                    reply_topic]))

        def submit(stream_id, frame_id):
            client_process.publish(
                f"{primary['topic']}/in",
                generate("process_frame", [
                    {"stream_id": stream_id, "frame_id": frame_id},
                    encode_frame_data(
                        {"number": frame_id}).encode("ascii")]))

        injector = create_injector(
            f"seed={seed};process_kill:node=gateway_a:"
            f"frame={kill_gateway}") if chaos else None
        try:
            for stream_id in stream_ids:
                create(stream_id)
            cursors = {stream_id: 0 for stream_id in stream_ids}
            for index in range(total):
                stream_id = stream_ids[index % streams_n]
                frame_id = cursors[stream_id]
                cursors[stream_id] += 1
                submit(stream_id, frame_id)
                if index in tick_frames:
                    # one wire-harvest control-loop tick on whoever is
                    # primary; the decide lands once every replica's
                    # publish_trace reply arrives (or the wait lease
                    # expires) -- deltas journal BEFORE they apply
                    gateway_a.post_message("_autopilot_collect", [])
                    time.sleep(1.0)
                if injector is not None and injector.process_kill(
                        "gateway_a"):
                    gateway_a.process.crash()
                time.sleep(0.004)
            expected = {(stream_id, frame_id)
                        for stream_id in stream_ids
                        for frame_id in range(per_stream)}
            deadline = time.monotonic() + (60 if SMOKE else 120)
            while time.monotonic() < deadline:
                with lock:
                    missing = expected - set(responses)
                if not missing:
                    break
                for stream_id in {key[0] for key in missing}:
                    create(stream_id)
                for stream_id, frame_id in sorted(missing):
                    submit(stream_id, frame_id)
                time.sleep(0.4)
            with lock:
                got = dict(responses)
            primary_pilot = gateway_a.autopilot
            standby_pilot = gateway_b.autopilot
            applied_seqs = [record["seq"]
                            for tick in primary_pilot.ledger
                            for record in tick.get("applied", [])]
            journaled = (gateway_b.journal.replay_deltas()
                         if gateway_b.journal is not None else [])

            def pilot_count(pilot, name):
                counter = pilot.registry._counters.get(name)
                return counter.value if counter is not None else 0

            return {
                "outputs": got,
                "frames_lost": len(expected) - len(got),
                "deltas_applied_primary": len(applied_seqs),
                "deltas_journaled": len(journaled),
                "deltas_adopted_standby": pilot_count(
                    standby_pilot, "autopilot.deltas_adopted"),
                "deltas_applied_standby": pilot_count(
                    standby_pilot, "autopilot.deltas_applied"),
                "config_restored": (
                    standby_pilot._applied == primary_pilot._applied
                    if chaos else None),
                "takeover_ms": (gateway_b.telemetry.last_takeover_ms
                                if chaos else None),
            }
        finally:
            for process in processes:
                try:
                    process.terminate()
                except Exception:
                    pass

    reference = run(chaos=False)
    reset_brokers()
    chaotic = run(chaos=True)
    reset_brokers()
    return {
        "seed": seed,
        "frames_total": total,
        "bit_identical_to_uncrashed": (
            chaotic["outputs"] == reference["outputs"]),
        "frames_lost": chaotic["frames_lost"],
        "frames_lost_reference": reference["frames_lost"],
        "deltas_applied_primary": chaotic["deltas_applied_primary"],
        "deltas_journaled": chaotic["deltas_journaled"],
        "deltas_adopted_standby": chaotic["deltas_adopted_standby"],
        "deltas_applied_standby": chaotic["deltas_applied_standby"],
        "config_restored": chaotic["config_restored"],
        "takeover_ms": chaotic["takeover_ms"],
        "topology": ("registrar + 1 wire-discovered replica + HA "
                     "gateway pair with retained delta journal, "
                     "loopback broker"),
    }


def bench_autopilot(peak, seed: int | None = None):
    """`autopilot` config: the online SLO control loop end to end.
    Arm 1 starts a deliberately mis-tuned fleet (micro_batch=16 for a
    closed-loop window of 2) and drives deterministic tick_now() loops:
    live trace harvest -> tune -> clamped deltas through the no-restart
    setter paths, converging to within `margin` of the hand-tuned
    reference with zero replica recompiles; the per-tick delta ledger
    is published.  Arm 2 kills the HA gateway primary in the apply
    window under seeded chaos: the standby adopts the write-ahead delta
    journal (every applied delta accounted, none re-applied) and the
    run stays bit-identical to an unkilled reference with
    frames_lost == 0.  Host-side (loopback broker): the numbers are
    control-loop quality bounds, not throughput figures."""
    seed = int(os.environ.get("AIKO_CHAOS_SEED", "11")
               if seed is None else seed)
    result = _autopilot_convergence_arm()
    result["policy"] = _AUTOPILOT_POLICY
    result["chaos"] = _autopilot_chaos_arm(seed)
    timeline_path = os.environ.get("AIKO_AUTOPILOT_TIMELINE")
    if timeline_path:
        try:
            with open(timeline_path, "w") as handle:
                json.dump(result, handle, indent=2)
            result["timeline_file"] = timeline_path
        except OSError as error:
            result["timeline_error"] = str(error)
    return result


# -- config 6b: continuous batching (decode/ engine) -------------------------

def bench_continuous(peak):
    """`continuous` config: the slot-based decode engine (decode/) vs
    the closed-batch generate() path under the SAME open-loop LLM
    traffic -- seeded ragged prompts/completion lengths arriving at 2x
    the engine's measured decode capacity.  The closed arm is the
    STRONGEST closed-batch server this repo can build (one warmed
    executable: fixed batch arity = `decode_slots` via zero-filler
    rows, one prompt bucket, fixed decode length), so the gap is the
    convoy/admission cost alone, not a compile artifact.  Published
    per arm: sustained goodput (useful tokens/sec until the backlog
    drains), TTFT p50/p99 (arrival -> first token), and -- continuous
    only -- mean/peak slot occupancy plus the compile counter across
    the measured window (must be 0: the zero-recompile guarantee)."""
    from collections import deque

    import jax
    import jax.numpy as jnp
    import numpy as np

    from aiko_services_tpu.decode import DecodeEngine
    from aiko_services_tpu.models import (
        count_params, generate_stream, init_params,
        transformer_flops_per_token)
    from aiko_services_tpu.models.configs import LLAMA32_1B, LM_TOY
    from aiko_services_tpu.utils.padding import bucket_length

    config = LM_TOY if SMOKE else LLAMA32_1B
    name = "lm_toy" if SMOKE else "llama32_1b"
    slots = 4 if SMOKE else 8
    block = 8 if SMOKE else 32
    requests_n = 24 if SMOKE else 96
    prompt_lo, prompt_hi = (4, 16) if SMOKE else (32, 128)
    new_lo, new_hi = (4, 24) if SMOKE else (16, 96)
    params = init_params(config, jax.random.PRNGKey(0))
    n_params = count_params(params)

    rng = np.random.default_rng(11)
    workload = [
        (rng.integers(1, config.vocab_size,
                      size=int(rng.integers(prompt_lo, prompt_hi + 1)))
         .astype(np.int32),
         int(rng.integers(new_lo, new_hi + 1)))
        for _ in range(requests_n)]
    mean_tokens = float(np.mean([new for _, new in workload]))
    prompt_bucket = bucket_length(prompt_hi, minimum=block)
    max_context = (-(-(prompt_bucket + new_hi) // block)) * block

    engine = DecodeEngine(params, config, decode_slots=slots,
                          kv_block_size=block, max_context=max_context)
    # engine warmup: one prompt per reachable prefill bucket + the
    # decode step, then a capacity probe with every slot busy
    length = block
    index = 0
    while length <= prompt_bucket:
        engine.submit(("warm", index), np.ones((length,), np.int32), 2)
        length, index = length * 2, index + 1
    while engine.has_work():
        engine.step()
    probe_steps = 8 if SMOKE else 32
    for index in range(slots):
        engine.submit(("probe", index),
                      np.ones((prompt_lo,), np.int32), probe_steps + 2)
    engine.step()  # admissions + first step outside the timed region
    probe_start = time.perf_counter()
    steps = 0
    while engine.has_work():
        steps += engine.step().active
    capacity_tok_s = steps / max(time.perf_counter() - probe_start, 1e-9)
    offered_req_s = 2.0 * capacity_tok_s / mean_tokens
    arrivals = np.cumsum(rng.exponential(1.0 / offered_req_s,
                                         size=requests_n))

    # -- continuous arm ----------------------------------------------------
    compiles_before = engine.compile_count
    ttft = {}
    occupancy = []
    tokens_done = 0
    next_index = 0
    start = time.perf_counter()
    while next_index < requests_n or engine.has_work():
        now = time.perf_counter() - start
        while (next_index < requests_n
               and arrivals[next_index] <= now):
            prompt, max_new = workload[next_index]
            engine.submit(next_index, prompt, max_new)
            next_index += 1
        if not engine.has_work():
            time.sleep(min(arrivals[next_index] - now, 0.01))
            continue
        report = engine.step()
        occupancy.append(report.active / slots)
        for request_id, offset, _ in report.emitted:
            if offset == 0:
                ttft[request_id] = (time.perf_counter() - start
                                    - arrivals[request_id])
        for completion in report.completions:
            tokens_done += completion.stats["tokens"]
    continuous_elapsed = time.perf_counter() - start
    continuous = {
        "goodput_tok_s": round(tokens_done / continuous_elapsed, 1),
        "ttft_p50_ms": round(float(np.percentile(
            list(ttft.values()), 50)) * 1000, 1),
        "ttft_p99_ms": round(float(np.percentile(
            list(ttft.values()), 99)) * 1000, 1),
        "slot_occupancy_mean": round(float(np.mean(occupancy)), 3),
        "slot_occupancy_peak": round(float(np.max(occupancy)), 3),
        "preempted": engine.counters["preempted"],
        "deferred_admissions": engine.counters["deferred_admissions"],
        "compiles_in_window": engine.compile_count - compiles_before,
    }

    # -- closed-batch arm --------------------------------------------------
    # one executable: batch always `slots` (zero-filler rows), prompts
    # padded to ONE bucket, decode length fixed at new_hi -- a member's
    # useful tokens stop at its own max_new, the rest of the batch's
    # steps are the convoy cost
    chunk = 4
    warm_prompt = jnp.ones((slots, prompt_bucket), jnp.int32)
    for _ in generate_stream(params, config, warm_prompt, new_hi,
                             chunk=chunk):
        pass
    waiting = deque()
    closed_ttft = {}
    tokens_done = 0
    batches = 0
    fill = []
    next_index = 0
    start = time.perf_counter()
    while next_index < requests_n or waiting:
        now = time.perf_counter() - start
        while (next_index < requests_n
               and arrivals[next_index] <= now):
            waiting.append(next_index)
            next_index += 1
        if not waiting:
            time.sleep(min(arrivals[next_index] - now, 0.01))
            continue
        members = [waiting.popleft()
                   for _ in range(min(slots, len(waiting)))]
        prompts = np.ones((slots, prompt_bucket), np.int32)
        for row, member in enumerate(members):
            prompt = workload[member][0]
            prompts[row, prompt_bucket - prompt.size:] = prompt  # left-pad
        first_block_at = None
        for _, block_tokens in generate_stream(
                params, config, jnp.asarray(prompts), new_hi,
                chunk=chunk):
            if first_block_at is None:
                np.asarray(block_tokens)  # force the prefill complete
                first_block_at = time.perf_counter() - start
        for member in members:
            closed_ttft[member] = first_block_at - arrivals[member]
            tokens_done += workload[member][1]  # useful tokens only
        batches += 1
        fill.append(len(members) / slots)
    closed_elapsed = time.perf_counter() - start
    closed = {
        "goodput_tok_s": round(tokens_done / closed_elapsed, 1),
        "ttft_p50_ms": round(float(np.percentile(
            list(closed_ttft.values()), 50)) * 1000, 1),
        "ttft_p99_ms": round(float(np.percentile(
            list(closed_ttft.values()), 99)) * 1000, 1),
        "batches": batches,
        "batch_fill_mean": round(float(np.mean(fill)), 3),
    }

    # -- mixed long-prefill arm (convoy measurability) ---------------------
    # a prompt 4x the standard bucket admitted mid-decode: without
    # chunking its monolithic prefill stalls every co-scheduled decode
    # slot for the whole kernel; with prefill_chunk_size = one bucket
    # the stall is bounded by a chunk.  Both arms must stay
    # bit-identical -- the convoy effect becomes a measured number the
    # chunked_prefill config (and ROADMAP #2 disaggregation) can be
    # judged against.
    long_len = 4 * prompt_bucket
    long_rng = np.random.default_rng(23)
    long_prompt = long_rng.integers(
        1, config.vocab_size, size=long_len).astype(np.int32)
    convoy_shorts = [
        long_rng.integers(1, config.vocab_size,
                          size=prompt_lo).astype(np.int32)
        for _ in range(slots - 1)]
    convoy_ctx = (-(-(long_len + new_hi)
                    // block)) * block
    convoy = {"long_prompt": long_len, "chunk": prompt_bucket,
              **_convoy_pair(
                  params, config, slots=slots, block=block,
                  chunk=prompt_bucket, short_prompts=convoy_shorts,
                  short_new=new_hi, long_prompt=long_prompt,
                  long_new=new_lo, max_context=convoy_ctx)}

    decode_flops = transformer_flops_per_token(config, prompt_hi)
    return {
        "model": f"{name} ({n_params / 1e6:.0f}M params)",
        "decode_slots": slots,
        "kv_block_size": block,
        "kv_blocks": engine.blocks.capacity,
        "max_context": engine.max_context,
        "requests": requests_n,
        "prompt_len": f"uniform {prompt_lo}..{prompt_hi}",
        "max_new": f"uniform {new_lo}..{new_hi}",
        "arrival": ("seeded exponential, open-loop at 2x measured "
                    "decode capacity"),
        "offered_req_s": round(offered_req_s, 2),
        "capacity_tok_s": round(capacity_tok_s, 1),
        "continuous": continuous,
        "closed_batch": closed,
        "long_prefill": convoy,
        "goodput_speedup": round(
            continuous["goodput_tok_s"]
            / max(closed["goodput_tok_s"], 1e-9), 2),
        "ttft_p99_speedup": round(
            closed["ttft_p99_ms"]
            / max(continuous["ttft_p99_ms"], 1e-9), 2),
        "decode_mfu": _mfu(continuous["goodput_tok_s"] * decode_flops,
                           peak),
    }


# -- configs 6c/6d: kernel-floor lifts (chunked prefill, spec decode) --------

def _engine_warmup(engine, lengths, max_new=2):
    """Compile every executable the measured phase will touch: one
    request per prompt bucket (which also walks the chunk buckets when
    chunking is on) plus the decode/verify steps."""
    import numpy as np

    for index, length in enumerate(lengths):
        engine.submit(("warm", index), np.ones((length,), np.int32),
                      max_new)
    while engine.has_work():
        engine.step()


def _convoy_arm(params, config, *, slots, block, chunk, short_prompts,
                short_new, long_prompt, long_new, max_context):
    """One convoy measurement: `slots-1` short requests decode in
    steady state, then one long prompt is admitted mid-flight.
    Returns (metrics, completion tokens) where decode_stall_max_ms is
    the longest wall gap between consecutive short-request token
    emissions after the long submission -- the convoy effect itself."""
    import numpy as np

    from aiko_services_tpu.decode import DecodeEngine

    engine = DecodeEngine(params, config, decode_slots=slots,
                          kv_block_size=block, max_context=max_context,
                          prefill_chunk_size=chunk)
    _engine_warmup(engine,
                   sorted({prompt.size for prompt in short_prompts}
                          | {long_prompt.size}))
    compiles_before = engine.compile_count
    outputs = {}
    for index, prompt in enumerate(short_prompts):
        engine.submit(("short", index), prompt, short_new)
    for _ in range(2):
        engine.step()  # shorts reach steady decode before the long lands
    engine.submit("long", long_prompt, long_new)
    submitted_at = time.perf_counter()
    last_short_emit = submitted_at
    max_gap = 0.0
    long_ttft = None
    while engine.has_work():
        report = engine.step()
        now = time.perf_counter()
        for request_id, offset, _token in report.emitted:
            if request_id == "long" and offset == 0:
                long_ttft = now - submitted_at
            if isinstance(request_id, tuple) and request_id[0] == "short":
                max_gap = max(max_gap, now - last_short_emit)
                last_short_emit = now
        for completion in report.completions:
            outputs[completion.request_id] = completion.tokens
    stats = engine.stats()
    return {
        "decode_stall_max_ms": round(max_gap * 1000, 2),
        "long_ttft_ms": round((long_ttft or 0.0) * 1000, 1),
        "prefill_chunks": stats["prefill_chunks"],
        "chunk_interleave_count": stats["chunk_interleaves"],
        "compiles_in_window": engine.compile_count - compiles_before,
    }, outputs


def _convoy_pair(params, config, *, chunk, **kwargs):
    """The monolithic/chunked A-B: both arms of _convoy_arm over the
    same workload, the stall ratio, and the bit-identity verdict --
    the ONE acceptance shape both the chunked_prefill config and the
    continuous config's long_prefill arm publish."""
    import numpy as np

    arms = {}
    arm_outputs = {}
    for label, chunk_size in (("monolithic", None), ("chunked", chunk)):
        arms[label], arm_outputs[label] = _convoy_arm(
            params, config, chunk=chunk_size, **kwargs)
    return {
        "monolithic": arms["monolithic"],
        "chunked": arms["chunked"],
        "stall_speedup": round(
            arms["monolithic"]["decode_stall_max_ms"]
            / max(arms["chunked"]["decode_stall_max_ms"], 1e-9), 2),
        "bit_identical": all(
            np.array_equal(arm_outputs["monolithic"][request_id],
                           arm_outputs["chunked"][request_id])
            for request_id in arm_outputs["monolithic"]),
    }


def bench_chunked_prefill(peak):
    """`chunked_prefill` config: the 16k-prefill kernel floor, engine
    view (ROADMAP #3a).  A long prompt admitted into a busy engine is
    measured twice -- monolithic paged_prefill (today's convoy: every
    decode slot stalls for the whole quadratic kernel) vs
    paged_prefill_chunk at a fixed chunk -- and the arms must be
    bit-identical.  Publishes the decode-stall bound, per-chunk cost,
    interleave counters, and zero-recompile proof; the committed
    `aiko tune` case study (reports/tune_chunked_prefill.json) carries
    the utilization-evidence shift at the recorded 16k operating
    point."""
    import jax
    import numpy as np

    from aiko_services_tpu.models import count_params, init_params
    from aiko_services_tpu.models.configs import LLAMA32_1B, LM_TOY

    config = LM_TOY if SMOKE else LLAMA32_1B
    name = "lm_toy" if SMOKE else "llama32_1b"
    slots = 4
    block = 8 if SMOKE else 32
    chunk = 32 if SMOKE else 512
    long_len = 192 if SMOKE else 3968
    short_len = 8 if SMOKE else 64
    short_new = 48 if SMOKE else 256
    long_new = 8 if SMOKE else 32
    params = init_params(config, jax.random.PRNGKey(0))
    rng = np.random.default_rng(17)
    short_prompts = [
        rng.integers(1, config.vocab_size, size=short_len)
        .astype(np.int32) for _ in range(slots - 1)]
    long_prompt = rng.integers(1, config.vocab_size,
                               size=long_len).astype(np.int32)
    max_context = (-(-(long_len + max(long_new, short_len + short_new))
                     // block)) * block
    pair = _convoy_pair(
        params, config, slots=slots, block=block, chunk=chunk,
        short_prompts=short_prompts, short_new=short_new,
        long_prompt=long_prompt, long_new=long_new,
        max_context=max_context)
    chunks_run = max(pair["chunked"]["prefill_chunks"], 1)
    return {
        "model": f"{name} ({count_params(params) / 1e6:.0f}M params)",
        "decode_slots": slots,
        "kv_block_size": block,
        "prefill_chunk_size": chunk,
        "long_prompt": long_len,
        "short_requests": f"{slots - 1} x {short_len} (+{short_new} new)",
        **pair,
        "chunk_interleave_count": pair["chunked"][
            "chunk_interleave_count"],
        # what an equal split of the monolithic kernel across the
        # chunk count would cost -- the per-call bound chunking targets
        "equiv_chunk_ms": round(
            pair["monolithic"]["long_ttft_ms"] / chunks_run, 2),
    }


def bench_spec_decode(peak):
    """`spec_decode` config: the decode weight-streaming floor, engine
    view (ROADMAP #3c).  Small-batch decode runs three arms over the
    SAME seeded workload -- plain greedy, speculative with a
    quarter-depth random-init draft (realistic overhead, low
    acceptance until a trained draft ships), and speculative with the
    target as its own draft (the acceptance CEILING: every window
    emits k+1 tokens per weight stream) -- all bit-identical.
    accepted_len_mean / draft_overhead_frac are the published
    telemetry the tune case study (reports/tune_spec_decode.json)
    turns into floor evidence."""
    import jax
    import numpy as np

    from dataclasses import replace

    from aiko_services_tpu.decode import DecodeEngine
    from aiko_services_tpu.models import count_params, init_params
    from aiko_services_tpu.models.configs import LLAMA32_1B, LM_TOY

    config = LM_TOY if SMOKE else LLAMA32_1B
    name = "lm_toy" if SMOKE else "llama32_1b"
    slots = 2 if SMOKE else 4      # batch 4 = the BENCH_NOTES floor row
    block = 8 if SMOKE else 32
    spec_k = 4
    requests_n = 6 if SMOKE else 24
    prompt_lo, prompt_hi = (4, 16) if SMOKE else (32, 128)
    max_new = 24 if SMOKE else 96
    params = init_params(config, jax.random.PRNGKey(0))
    draft_config = replace(config,
                           n_layers=max(1, config.n_layers // 4),
                           d_ff=max(64, config.d_ff // 2))
    draft_params = init_params(draft_config, jax.random.PRNGKey(1))
    rng = np.random.default_rng(19)
    workload = [
        rng.integers(1, config.vocab_size,
                     size=int(rng.integers(prompt_lo, prompt_hi + 1)))
        .astype(np.int32) for _ in range(requests_n)]
    warmup_lengths = sorted({prompt.size for prompt in workload})
    from aiko_services_tpu.utils.padding import bucket_length
    max_context = (-(-(bucket_length(prompt_hi, minimum=block)
                       + max_new + spec_k) // block)) * block

    def run(arm_draft_params, arm_draft_config):
        engine = DecodeEngine(
            params, config, decode_slots=slots, kv_block_size=block,
            max_context=max_context,
            draft_params=arm_draft_params,
            draft_config=arm_draft_config,
            spec_k=spec_k if arm_draft_params is not None else 0)
        _engine_warmup(engine, warmup_lengths)
        compiles_before = engine.compile_count
        outputs = {}
        tokens_done = 0
        start = time.perf_counter()
        for index, prompt in enumerate(workload):
            engine.submit(index, prompt, max_new)
        while engine.has_work():
            for completion in engine.step().completions:
                outputs[completion.request_id] = completion.tokens
                tokens_done += completion.stats["tokens"]
        elapsed = time.perf_counter() - start
        stats = engine.stats()
        block_stats = {
            "goodput_tok_s": round(tokens_done / elapsed, 1),
            "compiles_in_window":
                engine.compile_count - compiles_before,
        }
        if arm_draft_params is not None:
            block_stats["accepted_len_mean"] = stats[
                "accepted_len_mean"]
            block_stats["draft_overhead_frac"] = stats[
                "draft_overhead_frac"]
        return block_stats, outputs

    plain, plain_outputs = run(None, None)
    drafted, drafted_outputs = run(draft_params, draft_config)
    ceiling, ceiling_outputs = run(params, config)
    bit_identical = all(
        np.array_equal(plain_outputs[index], drafted_outputs[index])
        and np.array_equal(plain_outputs[index],
                           ceiling_outputs[index])
        for index in plain_outputs)
    return {
        "model": f"{name} ({count_params(params) / 1e6:.0f}M params)",
        "draft": (f"{draft_config.n_layers}L/{draft_config.d_ff}ff "
                  f"random-init "
                  f"({count_params(draft_params) / 1e6:.0f}M params)"),
        "decode_slots": slots,
        "kv_block_size": block,
        "spec_k": spec_k,
        "requests": requests_n,
        "prompt_len": f"uniform {prompt_lo}..{prompt_hi}",
        "max_new": max_new,
        "plain": plain,
        "speculative": drafted,
        "self_draft_ceiling": ceiling,
        "accepted_len_mean": drafted["accepted_len_mean"],
        "draft_overhead_frac": drafted["draft_overhead_frac"],
        "goodput_speedup": round(
            drafted["goodput_tok_s"]
            / max(plain["goodput_tok_s"], 1e-9), 2),
        "ceiling_speedup": round(
            ceiling["goodput_tok_s"]
            / max(plain["goodput_tok_s"], 1e-9), 2),
        "bit_identical": bit_identical,
    }


# -- config 6e: cross-request prefix KV reuse --------------------------------

def _prefix_cache_definition(name, max_new=16, slots=4):
    """One prefix-caching continuous decode replica: the definition the
    `prefix_cache` config exercises, also collected into the `aiko lint
    --bench` surface so its AIKO405/411 parameter set stays strict-mode
    clean."""
    return {
        "name": name,
        "parameters": {"telemetry": TELEMETRY,
                       "metrics_interval": 60.0},
        "graph": ["(lm)"],
        "elements": [
            {"name": "lm",
             "input": [{"name": "tokens", "type": "any"}],
             "output": [{"name": "generated", "type": "any"}],
             "parameters": {
                 "vocab_size": 300, "d_model": 32, "n_layers": 1,
                 "n_heads": 2, "n_kv_heads": 1, "d_ff": 64,
                 "max_seq_len": 128, "dtype": "float32",
                 "max_new_tokens": max_new, "continuous": True,
                 "decode_slots": slots, "kv_block_size": 8,
                 "stream_tokens": True, "stream_chunk": 1,
                 "prefix_policy": ("prefix_cache=on;"
                                   "min_prefix_blocks=1;"
                                   "cache_blocks=32")},
             "deploy": {"local": {"module": ELEMENTS,
                                  "class_name": "LMGenerate"}}},
        ],
    }


def bench_prefix_cache(peak):
    """`prefix_cache` config: cross-request prefix KV reuse
    (decode/prefix.py).  A shared-system-prompt storm -- every request
    is the same long prefix plus a unique fixed-length tail -- runs
    twice over the SAME seeded workload: cold (no prefix policy, every
    prompt pays the full quadratic prefill) vs warm (prefix_cache=on,
    repeat prompts borrow the cached prompt blocks and prefill only
    the tail).  Requests are submitted sequentially, so per-request
    TTFT is the prefill cost itself; the arms must be BIT-IDENTICAL
    (f32 AND int8 KV) with zero warm-arm recompiles in the measured
    window.  A third stage A/Bs the gateway's prefix-affinity routing
    (serve/gateway.py _place) over two replica caches: the on arm must
    beat hint-blind power-of-two routing on aggregate hit rate."""
    import jax
    import numpy as np

    from dataclasses import replace

    from aiko_services_tpu.decode import DecodeEngine, prefix_head
    from aiko_services_tpu.models import count_params, init_params
    from aiko_services_tpu.models.configs import LLAMA32_1B, LM_TOY
    from aiko_services_tpu.runtime import Process
    from aiko_services_tpu.serve import Gateway
    from aiko_services_tpu.serve.gateway import _Replica
    from aiko_services_tpu.transport import reset_brokers

    config = LM_TOY if SMOKE else LLAMA32_1B
    name = "lm_toy" if SMOKE else "llama32_1b"
    slots = 2 if SMOKE else 4
    block = 8 if SMOKE else 32
    prefix_len = 32 if SMOKE else 1024   # the shared system prompt
    tail_len = 8 if SMOKE else 64        # fixed: one tail chunk bucket
    requests_n = 6 if SMOKE else 16
    max_new = 8 if SMOKE else 32
    armed = "prefix_cache=on"
    params = init_params(config, jax.random.PRNGKey(0))
    rng = np.random.default_rng(29)
    system = rng.integers(1, config.vocab_size,
                          size=prefix_len).astype(np.int32)
    workload = [
        np.concatenate([system,
                        rng.integers(1, config.vocab_size,
                                     size=tail_len).astype(np.int32)])
        for _ in range(requests_n)]
    total_len = prefix_len + tail_len
    max_context = (-(-(total_len + max_new) // block)) * block

    def run_arm(arm_config, arm_params, prefix_policy):
        engine = DecodeEngine(
            arm_params, arm_config, decode_slots=slots,
            kv_block_size=block, max_context=max_context,
            prefix_policy=prefix_policy)
        # warmup compiles BOTH prefill shapes the window touches: the
        # cold monolithic bucket and (when armed) the warm tail chunk
        # -- the probe prompt repeats so the second run takes the
        # cache-hit path, then the cache is dropped so the measured
        # window starts cold
        probe = np.ones((total_len,), np.int32)
        _engine_warmup(engine, [total_len])
        engine.submit(("warm", 1), probe, 2)
        while engine.has_work():
            engine.step()
        if engine.prefix is not None:
            engine.prefix.drop()
        compiles_before = engine.compile_count
        hits_before = engine.counters["prefix_hits"]
        shared_before = engine.counters["prefix_blocks_shared"]
        outputs, ttfts = {}, []
        for index, prompt in enumerate(workload):
            engine.submit(index, prompt, max_new)
            while engine.has_work():
                for completion in engine.step().completions:
                    outputs[completion.request_id] = completion.tokens
                    ttfts.append(completion.stats["ttft_s"] * 1000)
        return {
            "ttft_p50_ms": round(float(np.median(ttfts)), 2),
            "ttft_p99_ms": round(float(np.quantile(ttfts, 0.99)), 2),
            "compiles_in_window":
                engine.compile_count - compiles_before,
            "prefix_hits": engine.counters["prefix_hits"] - hits_before,
            "blocks_shared": (engine.counters["prefix_blocks_shared"]
                              - shared_before),
            "evictions": (engine.prefix.evictions
                          if engine.prefix is not None else 0),
        }, outputs

    cold, cold_outputs = run_arm(config, params, None)
    warm, warm_outputs = run_arm(config, params, armed)
    warm["hit_rate"] = round(warm["prefix_hits"] / requests_n, 3)
    bit_identical_f32 = all(
        np.array_equal(cold_outputs[index], warm_outputs[index])
        for index in cold_outputs)

    # int8 KV: the shared blocks carry their per-block scales, so the
    # warm path must round-trip the quantized cache bit-exactly too
    int8_config = replace(config, kv_dtype="int8")
    int8_params = init_params(int8_config, jax.random.PRNGKey(0))
    int8_cold, int8_cold_outputs = run_arm(int8_config, int8_params,
                                           None)
    int8_warm, int8_warm_outputs = run_arm(int8_config, int8_params,
                                           armed)
    bit_identical_int8 = all(
        np.array_equal(int8_cold_outputs[index],
                       int8_warm_outputs[index])
        for index in int8_cold_outputs)

    def affinity_arm(use_affinity):
        """Two replica caches behind the REAL _place scoring: seeded
        per-group prompts, sequential streams, each replica mirroring
        its chain heads the way elements/ml.py publishes them."""
        reset_brokers()
        groups = 3 if SMOKE else 4
        per_group = 4 if SMOKE else 8
        arm_rng = np.random.default_rng(31)
        prefixes = [arm_rng.integers(1, 300, size=16).astype(np.int32)
                    for _ in range(groups)]
        toy = replace(LM_TOY, vocab_size=300)
        toy_params = init_params(toy, jax.random.PRNGKey(2))
        gateway = Gateway(
            Process(transport_kind="loopback"),
            policy="max_inflight=8;queue=32", router_seed=23,
            prefix=("prefix_cache=on;affinity_weight=2"
                    if use_affinity else None))
        engines, mirrors = {}, {}
        for replica_name in ("r0", "r1"):
            engines[replica_name] = DecodeEngine(
                toy_params, toy, decode_slots=2, kv_block_size=8,
                prefix_policy=armed)
            mirror = _Replica(f"bench/{replica_name}", replica_name,
                              cache={"inflight": 0, "prefix_heads": ""})
            mirrors[replica_name] = mirror
            gateway.replicas[mirror.topic_path] = mirror
        placed, hits = 0, 0
        for round_index in range(per_group):
            for group, prefix in enumerate(prefixes):
                prompt = np.concatenate([
                    prefix, arm_rng.integers(1, 300, size=8)
                    .astype(np.int32)])
                hint = prefix_head(prompt, 8)
                chosen = gateway._place(
                    0.0, prefix_hint=hint if use_affinity else None)
                engine = engines[chosen.name]
                before = engine.counters["prefix_hits"]
                engine.submit((group, round_index), prompt, 2)
                while engine.has_work():
                    engine.step()
                hits += engine.counters["prefix_hits"] - before
                placed += 1
                mirrors[chosen.name].cache["prefix_heads"] = ",".join(
                    engine.prefix_heads())
        return round(hits / placed, 3)

    affinity_on = affinity_arm(True)
    affinity_off = affinity_arm(False)

    return {
        "model": f"{name} ({count_params(params) / 1e6:.0f}M params)",
        "decode_slots": slots,
        "kv_block_size": block,
        "shared_prefix_len": prefix_len,
        "tail_len": tail_len,
        "requests": requests_n,
        "max_new": max_new,
        "cold": cold,
        "warm": warm,
        "int8": {"cold_ttft_p50_ms": int8_cold["ttft_p50_ms"],
                 "warm_ttft_p50_ms": int8_warm["ttft_p50_ms"],
                 "prefix_hits": int8_warm["prefix_hits"]},
        "prefix_hits": warm["prefix_hits"],
        "hit_rate": warm["hit_rate"],
        "blocks_shared": warm["blocks_shared"],
        "ttft_collapse": round(
            cold["ttft_p50_ms"] / max(warm["ttft_p50_ms"], 1e-9), 2),
        "compiles_in_window": warm["compiles_in_window"],
        "bit_identical": bit_identical_f32 and bit_identical_int8,
        "bit_identical_f32": bit_identical_f32,
        "bit_identical_int8": bit_identical_int8,
        "affinity": {
            "on_hit_rate": affinity_on,
            "off_hit_rate": affinity_off,
            "advantage": round(affinity_on - affinity_off, 3),
        },
    }


# -- config 6f: prefill/decode disaggregation --------------------------------

def bench_disagg(peak):
    """`disagg` config: prefill/decode disaggregation (ROADMAP #2,
    decode/disagg.py) vs colocation under a MIXED long-prefill +
    long-decode storm.

    Four arms over one seeded workload of short decode-heavy requests,
    with periodic LONG prompts landing mid-run:

      unloaded    decode requests only -- the TTFT baseline disagg is
                  judged against
      colocated   long prompts prefill ON the decode engine: each
                  monolithic prefill kernel convoys every co-scheduled
                  decode slot (the measured cost of colocation)
      disagg      long prompts prefill on a PrefillEngine running on
                  its own thread (the prefill replica); the finished
                  prompt's KV blocks migrate over the transfer plane
                  and the decode engine ADOPTS them mid-flight
      disagg_2x   the same split with the decode load DOUBLED -- the
                  acceptance shape: decode TTFT p99 stays flat
                  (<= 1.2x unloaded) as decode load doubles

    Every arm's tokens must be bit-identical to the co-located
    continuous engine, zero requests lost, zero decode-engine
    recompiles in the measured window; the disagg arms publish KV
    migration bytes and the adopt-latency histogram."""
    import threading
    import queue as queue_module

    import jax
    import numpy as np

    from aiko_services_tpu.decode import DecodeEngine, PrefillEngine
    from aiko_services_tpu.models import (
        count_params, init_params, transformer_flops_per_token)
    from aiko_services_tpu.models.configs import LLAMA32_1B, LM_TOY
    from aiko_services_tpu.observe.metrics import MetricsRegistry
    from aiko_services_tpu.utils.padding import bucket_length

    config = LM_TOY if SMOKE else LLAMA32_1B
    name = "lm_toy" if SMOKE else "llama32_1b"
    slots = 4 if SMOKE else 8
    block = 8 if SMOKE else 32
    decode_n = 16 if SMOKE else 64
    prompt_lo, prompt_hi = (4, 8) if SMOKE else (16, 48)
    new_lo, new_hi = (8, 16) if SMOKE else (32, 96)
    longs_n = 3 if SMOKE else 8
    params = init_params(config, jax.random.PRNGKey(0))
    prompt_bucket = bucket_length(prompt_hi, minimum=block)
    long_len = 4 * prompt_bucket
    long_new = new_lo
    max_context = (-(-(long_len + new_hi) // block)) * block

    rng = np.random.default_rng(17)
    decode_work = [
        (rng.integers(1, config.vocab_size,
                      size=int(rng.integers(prompt_lo, prompt_hi + 1)))
         .astype(np.int32),
         int(rng.integers(new_lo, new_hi + 1)))
        for _ in range(2 * decode_n)]   # the 2x arm uses the full list
    long_prompts = [
        rng.integers(1, config.vocab_size,
                     size=long_len).astype(np.int32)
        for _ in range(longs_n)]
    mean_tokens = float(np.mean([new for _, new in decode_work]))

    warm_lengths = []
    length = block
    while length <= bucket_length(long_len, minimum=block):
        warm_lengths.append(length)
        length *= 2

    def build_engine(registry=None):
        engine = DecodeEngine(params, config, decode_slots=slots,
                              kv_block_size=block,
                              max_context=max_context,
                              registry=registry)
        _engine_warmup(engine, warm_lengths)
        return engine

    # capacity probe (throwaway engine): sets the open-loop offered
    # rate so the 1x arm runs AT capacity and the 2x arm at twice it
    probe = build_engine()
    for index in range(slots):
        probe.submit(("probe", index),
                     np.ones((prompt_lo,), np.int32), 10)
    probe.step()
    probe_start = time.perf_counter()
    steps = 0
    while probe.has_work():
        steps += probe.step().active
    capacity_tok_s = steps / max(time.perf_counter() - probe_start,
                                 1e-9)
    # base load at 0.4x measured capacity: the acceptance shape doubles
    # the decode load, and flat TTFT is only a meaningful claim while
    # the doubled pool is still below saturation (at/over capacity the
    # backlog itself -- not prefill convoying -- owns the p99)
    offered_req_s = 0.4 * capacity_tok_s / mean_tokens

    def run_arm(load: int, with_longs: bool, disagg: bool):
        registry = MetricsRegistry()
        engine = build_engine(registry)
        count = decode_n * load
        arrivals = np.cumsum(np.random.default_rng(29).exponential(
            1.0 / (offered_req_s * load), size=count))
        span = float(arrivals[-1])
        long_arrivals = [span * (index + 1) / (longs_n + 1)
                         for index in range(longs_n)] if with_longs \
            else []
        prefill_engine = None
        handoffs: queue_module.Queue = queue_module.Queue()
        stop = threading.Event()
        worker = None
        if disagg:
            prefill_engine = PrefillEngine(
                params, config, kv_block_size=block,
                max_context=max_context, registry=registry)
            # warm BOTH halves of the migration outside the window:
            # the prefill executables, the batched fetch, and the
            # decode pool's adopt scatter all compile here, not on the
            # first measured long prompt
            prefill_engine.submit(("warm", 0),
                                  np.ones((long_len,), np.int32), 2)
            while prefill_engine.has_work():
                for warm_handoff in prefill_engine.step():
                    engine.adopt_request(("warm", "adopt"),
                                         warm_handoff, timeout=5)
            while engine.has_work():
                engine.step()

            def pump():
                # the prefill REPLICA: its own thread, its own pool --
                # prompt kernels never touch the decode engine's slots
                while not stop.is_set():
                    if prefill_engine.has_work():
                        for handoff in prefill_engine.step():
                            handoffs.put(handoff)
                    else:
                        time.sleep(0.0005)

            worker = threading.Thread(target=pump, daemon=True)
            worker.start()
        compiles_before = engine.compile_count
        ttft = {}
        outputs = {}
        submitted = set()
        next_decode = 0
        next_long = 0
        start = time.perf_counter()

        def pending_longs():
            return (next_long < len(long_arrivals)
                    or (prefill_engine is not None
                        and (prefill_engine.has_work()
                             or not handoffs.empty())))

        while (next_decode < count or pending_longs()
               or engine.has_work()):
            now = time.perf_counter() - start
            while next_decode < count and arrivals[next_decode] <= now:
                prompt, max_new = decode_work[next_decode]
                engine.submit(("d", next_decode), prompt, max_new)
                submitted.add(("d", next_decode))
                next_decode += 1
            while (next_long < len(long_arrivals)
                   and long_arrivals[next_long] <= now):
                request_id = ("long", next_long)
                submitted.add(request_id)
                if disagg:
                    prefill_engine.submit(request_id,
                                          long_prompts[next_long],
                                          long_new)
                else:
                    engine.submit(request_id,
                                  long_prompts[next_long], long_new)
                next_long += 1
            if disagg:
                # adopt only INTO free slots: a saturated engine holds
                # the handoff (the transfer server keeps the blocks
                # fetchable) instead of burning a fallback re-prefill
                while any(slot is None for slot in engine.slots):
                    try:
                        handoff = handoffs.get_nowait()
                    except queue_module.Empty:
                        break
                    report = engine.adopt_request(
                        handoff["request_id"], handoff, timeout=5)
                    for request_id, offset, _token in report.emitted:
                        if offset == 0:
                            ttft[request_id] = (
                                time.perf_counter() - start)
                    for completion in report.completions:
                        outputs[completion.request_id] = \
                            completion.tokens
            if not engine.has_work():
                time.sleep(0.001)
                continue
            report = engine.step()
            now = time.perf_counter() - start
            for request_id, offset, _token in report.emitted:
                if offset == 0:
                    ttft[request_id] = now
            for completion in report.completions:
                outputs[completion.request_id] = completion.tokens
        elapsed = time.perf_counter() - start
        stop.set()
        if worker is not None:
            worker.join(timeout=5)
        # TTFT relative to each request's ARRIVAL, decode requests only
        decode_ttft = [
            ttft[("d", index)] - arrivals[index]
            for index in range(count) if ("d", index) in ttft]
        stats = {
            "requests": count,
            "completed": len(outputs),
            "lost": len(submitted) - len(outputs),
            "elapsed_s": round(elapsed, 2),
            "ttft_p50_ms": round(float(np.percentile(
                decode_ttft, 50)) * 1000, 1),
            "ttft_p99_ms": round(float(np.percentile(
                decode_ttft, 99)) * 1000, 1),
            "compiles_in_window": engine.compile_count
            - compiles_before,
        }
        if disagg:
            adopt = registry.histogram("decode.adopt_ms")
            stats["adopted"] = engine.counters["adopted"]
            stats["adopt_fallbacks"] = engine.counters[
                "adopt_fallbacks"]
            stats["kv_migrated_bytes"] = engine.counters[
                "kv_migrated_bytes"]
            if adopt.count:
                stats["adopt_ms_p50"] = round(adopt.quantile(0.5), 3)
                stats["adopt_ms_p99"] = round(adopt.quantile(0.99), 3)
            stats["prefill_exports"] = prefill_engine.counters[
                "exported"]
        return stats, outputs

    unloaded, _ = run_arm(1, with_longs=False, disagg=False)
    unloaded_2x, _ = run_arm(2, with_longs=False, disagg=False)
    colocated, colocated_out = run_arm(1, with_longs=True,
                                       disagg=False)
    disagg_1x, disagg_out = run_arm(1, with_longs=True, disagg=True)
    disagg_2x, disagg_2x_out = run_arm(2, with_longs=True, disagg=True)
    bit_identical = all(
        np.array_equal(colocated_out[request_id],
                       disagg_out[request_id])
        for request_id in colocated_out) and all(
        np.array_equal(disagg_2x_out[request_id],
                       colocated_out[request_id])
        for request_id in colocated_out)
    frames_lost = (colocated["lost"] + disagg_1x["lost"]
                   + disagg_2x["lost"] + unloaded["lost"])
    decode_flops = transformer_flops_per_token(config, prompt_hi)
    return {
        "model": f"{name} ({count_params(params) / 1e6:.0f}M params)",
        "decode_slots": slots,
        "kv_block_size": block,
        "max_context": max_context,
        "decode_requests": decode_n,
        "long_prefills": longs_n,
        "long_prompt": long_len,
        "prompt_len": f"uniform {prompt_lo}..{prompt_hi}",
        "max_new": f"uniform {new_lo}..{new_hi}",
        "arrival": ("seeded exponential, open-loop at measured decode "
                    "capacity (2x in the disagg_2x arm)"),
        "offered_req_s": round(offered_req_s, 2),
        "capacity_tok_s": round(capacity_tok_s, 1),
        "unloaded": unloaded,
        "unloaded_2x": unloaded_2x,
        "colocated": colocated,
        "disagg": disagg_1x,
        "disagg_2x": disagg_2x,
        "bit_identical": bit_identical,
        "frames_lost": frames_lost,
        "kv_migrated_bytes": disagg_1x.get("kv_migrated_bytes", 0)
        + disagg_2x.get("kv_migrated_bytes", 0),
        "adopt_ms_p50": disagg_1x.get("adopt_ms_p50"),
        "adopt_ms_p99": disagg_1x.get("adopt_ms_p99"),
        # the acceptance shape: the long-prefill storm must not move
        # decode TTFT p99 off its SAME-LOAD unloaded baseline as the
        # decode load doubles -- queueing from decode load itself
        # appears on both sides of each ratio, so what remains is the
        # prefill convoy, which is exactly what disaggregation removes
        # (the colocated ratio measures that convoy uncorrected)
        "ttft_p99_vs_unloaded_1x": round(
            disagg_1x["ttft_p99_ms"]
            / max(unloaded["ttft_p99_ms"], 1e-9), 2),
        "ttft_p99_vs_unloaded_2x": round(
            disagg_2x["ttft_p99_ms"]
            / max(unloaded_2x["ttft_p99_ms"], 1e-9), 2),
        "colocated_ttft_p99_ratio": round(
            colocated["ttft_p99_ms"]
            / max(unloaded["ttft_p99_ms"], 1e-9), 2),
        "ttft_p99_flat": (
            disagg_2x["ttft_p99_ms"]
            <= 1.2 * max(unloaded_2x["ttft_p99_ms"], 1e-9)),
        "decode_mfu": _mfu(capacity_tok_s * decode_flops, peak),
    }


# -- config 7: TTS -----------------------------------------------------------

def _tts_definition(phrase, batch, count):
    return {
        "name": "bench_tts",
        "graph": ["(source (tts))"],
        "elements": [
            {"name": "source",
             "output": [{"name": "text", "type": "str"},
                        {"name": "t0", "type": "float"}],
             "parameters": {"data_sources": [phrase],
                            "data_batch_size": batch,
                            "timestamps": True,
                            "count": count},
             "deploy": _local("TextSource")},
            {"name": "tts",
             "input": [{"name": "text", "type": "str"}],
             # waveform length depends on the phrase's char bucket:
             # rank+dtype are the provable contract, the sample axis
             # stays a wildcard
             "output": [{"name": "audio", "type": "f32[b,*]"},
                        {"name": "sample_rate", "type": "int"}],
             "deploy": _local("TextToSpeech")},
        ],
    }


# -- scale: ten-thousand-stream control-plane scale-out ----------------------

# one spec, three surfaces: the running gateways, the definition
# parameter `aiko lint --bench` checks (AIKO403/AIKO410), and the
# published config block.  max_inflight is sized so the storm never
# parks (the bounded parked queue's linear scans are the OLD ceiling
# this config exists to measure past); the queue is a backstop only.
_SCALE_POLICY = "max_inflight=16384;queue=2048"
_SCALE_GROUPS = ("g0", "g1", "g2", "g3")
_SCALE_FEDERATION = f"groups={','.join(_SCALE_GROUPS)}"


class _ControlPlaneMeter:
    """Control-plane cost window around one config's run: broker
    message rate, registrar registration qps, and EC share sync rate
    from the process-global counter deltas -- published as the
    `control_plane` sub-block of every pipeline-running config so
    future `aiko tune` work can see the control plane's share of each
    workload."""

    def __init__(self):
        from aiko_services_tpu.observe.metrics import get_registry
        self._registry = get_registry()
        self._start = time.perf_counter()
        self._before = dict(self._registry.snapshot()["counters"])

    def block(self) -> dict:
        counters = self._registry.snapshot()["counters"]
        elapsed = max(time.perf_counter() - self._start, 1e-9)

        def delta(name):
            return counters.get(name, 0) - self._before.get(name, 0)

        broker_msgs = delta("broker.messages")
        registrar_ops = delta("registrar.adds") + delta(
            "registrar.removes")
        ec_syncs = delta("share.publishes")
        return {
            "window_s": round(elapsed, 3),
            "broker_msgs": broker_msgs,
            "broker_msgs_per_s": round(broker_msgs / elapsed, 1),
            "broker_fanout_avoided": delta("broker.fanout_avoided"),
            "registrar_ops": registrar_ops,
            "registrar_qps": round(registrar_ops / elapsed, 1),
            "ec_syncs": ec_syncs,
            "ec_syncs_per_s": round(ec_syncs / elapsed, 1),
            "ec_updates_coalesced": delta("share.updates_coalesced"),
            "ec_delta_publishes": delta("share.delta_publishes"),
        }


def _with_control_plane(bench_fn, *args):
    """Run one config with a control-plane cost window around it."""
    meter = _ControlPlaneMeter()
    block = bench_fn(*args)
    if isinstance(block, dict):
        block["control_plane"] = meter.block()
    return block


def _scale_definition(name):
    """Device-light echo element: the scale storm measures the CONTROL
    plane (broker matching, gateway routing, EC syncs), so the data
    plane is one integer add per frame."""
    return {
        "name": name,
        "parameters": {"telemetry": False,
                       "gateway_policy": _SCALE_POLICY,
                       "federation_policy":
                           f"{_SCALE_FEDERATION};group=g0"},
        "graph": ["(echo)"],
        "elements": [
            {"name": "echo",
             "input": [{"name": "number", "type": "int"}],
             "output": [{"name": "number", "type": "int"}],
             "parameters": {"constant": 1},
             "deploy": _local("PE_Add")},
        ],
    }


def _scale_ab_arm(mode: str, subscriptions, messages):
    """One trie-vs-linear A/B arm: a dedicated loopback broker in
    `mode`, C clients with deterministic wildcard subscription sets,
    K deterministic publishes.  Returns (per-client delivery lists,
    mean per-message match seconds from the broker.match_s delta)."""
    from aiko_services_tpu.observe.metrics import get_registry
    from aiko_services_tpu.transport.loopback import (
        LoopbackTransport, get_broker)

    broker = get_broker(f"scale_ab_{mode}")
    broker.match_mode = mode
    clients = []
    for patterns in subscriptions:
        received = []
        transport = LoopbackTransport(
            on_message=(lambda topic, payload, received=received:
                        received.append((topic, payload))),
            broker=f"scale_ab_{mode}")
        for pattern in patterns:
            transport.subscribe(pattern)
        transport.connect()
        clients.append(received)
    histogram = get_registry().histogram("broker.match_s")
    count_before, sum_before = histogram.count, histogram.total
    start = time.perf_counter()
    for topic, payload in messages:
        broker.publish(topic, payload)
    broker.drain(timeout=60)
    elapsed = max(time.perf_counter() - start, 1e-9)
    matched = max(histogram.count - count_before, 1)
    mean_match_s = (histogram.total - sum_before) / matched
    return ([list(received) for received in clients], mean_match_s,
            len(messages) / elapsed)


def bench_scale(peak):
    """`scale` config (ROADMAP #5): O(10k) lightweight open-loop
    streams through a FEDERATED gateway tier -- multiple gateway
    groups, streams assigned by consistent hash of stream id, one
    shared device-light replica fleet -- with the broker and registrar
    measured as the control-plane ceiling.  Publishes goodput / shed /
    p99 (frames_lost must be 0: every offered frame answers exactly
    once), the new `broker.*` counters (messages, matched-fanout
    ratio, match latency), and a trie-vs-linear A/B arm proving the
    broker match fast path is FASTER and delivery-identical (same
    messages, same per-topic order)."""
    import threading

    import numpy as np

    from aiko_services_tpu.observe.metrics import (
        get_registry, snapshot_quantile)
    from aiko_services_tpu.pipeline import create_pipeline
    from aiko_services_tpu.runtime import Process
    from aiko_services_tpu.serve import FederationRouter, Gateway
    from aiko_services_tpu.transport import TopicTrie, topic_matches

    streams_n = int(os.environ.get(
        "AIKO_SCALE_STREAMS", "1500" if SMOKE else "6000"))
    frames_per_stream = 2
    groups = list(_SCALE_GROUPS[:2 if SMOKE else len(_SCALE_GROUPS)])
    replicas_n = 2
    offered = streams_n * frames_per_stream
    # broker counters window: the WHOLE config (A/B arms included --
    # the storm itself rides the in-process fast paths, so the arms
    # supply the broker's own matching traffic)
    registry = get_registry()
    before = dict(registry.snapshot()["counters"])
    match_before = registry.histogram("broker.match_s").snapshot()

    # -- trie-vs-linear A/B (deterministic corpus, dedicated brokers) --
    rng = random.Random(23)
    corpus = ([f"t/{index}" for index in range(64)]
              + [f"t/{index}/+" for index in range(16)]
              + [f"grp/{index}/#" for index in range(16)]
              + ["t/#", "+/0", "grp/+/state"])
    subscriptions = [rng.sample(corpus, 6) for _ in range(48)]
    topics = ([f"t/{rng.randrange(64)}" for _ in range(1500)]
              + [f"grp/{rng.randrange(16)}/state" for _ in range(500)])
    messages = [(topic, f"m{index}")
                for index, topic in enumerate(topics)]
    trie_deliveries, trie_match_s, trie_msgs_per_s = _scale_ab_arm(
        "trie", subscriptions, messages)
    linear_deliveries, linear_match_s, linear_msgs_per_s = (
        _scale_ab_arm("linear", subscriptions, messages))
    ab_identical = trie_deliveries == linear_deliveries
    # direct matcher micro-bench over the same corpus: one trie walk
    # vs the full linear pattern scan per message
    flat = [(pattern, (client, pattern))
            for client, patterns in enumerate(subscriptions)
            for pattern in patterns]
    trie = TopicTrie()
    for pattern, value in flat:
        trie.add(pattern, value)
    start = time.perf_counter()
    for topic, _ in messages:
        trie.match(topic)
    micro_trie_s = (time.perf_counter() - start) / len(messages)
    start = time.perf_counter()
    for topic, _ in messages:
        [value for pattern, value in flat
         if topic_matches(pattern, topic)]
    micro_linear_s = (time.perf_counter() - start) / len(messages)

    # -- the federated storm -------------------------------------------
    processes, replicas = [], []
    for index in range(replicas_n):
        process = Process(transport_kind="loopback")
        processes.append(process)
        replicas.append(create_pipeline(
            process, _scale_definition(f"scale_replica{index}")))
    gateways = {}
    for group in groups:
        process = Process(transport_kind="loopback")
        processes.append(process)
        gateways[group] = Gateway(
            process, name=f"gw_{group}", policy=_SCALE_POLICY,
            federation=f"groups={','.join(groups)};group={group}",
            telemetry=False)
        for replica in replicas:
            gateways[group].attach_replica(replica)
    router = FederationRouter(gateways)
    for process in processes:
        process.run(in_thread=True)

    responses = queue.Queue()
    submit_times = {}
    latencies = []
    counts = {"ok": 0, "shed": 0, "overloaded": 0, "error": 0}
    done = threading.Event()

    def drain():
        for _ in range(offered):
            stream_id, frame_id, _outputs, status = responses.get(
                timeout=900)
            if status == "ok":
                submitted = submit_times.pop((stream_id, frame_id),
                                             None)
                if submitted is not None:
                    latencies.append(time.perf_counter() - submitted)
            counts[status if status in counts else "error"] += 1
        done.set()

    start = time.perf_counter()
    for index in range(streams_n):
        router.submit_stream(f"s{index}", queue_response=responses,
                             grace_time=1800)
    drainer = threading.Thread(target=drain, daemon=True)
    drainer.start()
    # open loop: every frame submitted without waiting on completions
    for frame_id in range(frames_per_stream):
        for index in range(streams_n):
            stream_id = f"s{index}"
            submit_times[(stream_id, frame_id)] = time.perf_counter()
            router.submit_frame(stream_id, {"number": index},
                                frame_id=frame_id)
    done.wait(timeout=900)
    elapsed = time.perf_counter() - start
    # streams are never destroyed mid-storm: the live count at drain
    # time IS the concurrency the config claims
    streams_live = sum(
        len(gateway.streams) for gateway in gateways.values())
    counters = registry.snapshot()["counters"]
    match_after = registry.histogram("broker.match_s").snapshot()

    def delta(name):
        return counters.get(name, 0) - before.get(name, 0)

    match_delta = {
        "count": match_after["count"] - match_before["count"],
        "sum": match_after["sum"] - match_before["sum"],
        "min": match_after["min"], "max": match_after["max"],
        "buckets": [late - early for late, early in zip(
            match_after["buckets"], match_before["buckets"])],
    }
    delivered = delta("broker.fanout_delivered")
    avoided = delta("broker.fanout_avoided")
    shed = counts["shed"] + counts["overloaded"]
    frames_lost = offered - counts["ok"] - shed - counts["error"]
    for process in processes:
        process.terminate()
    return {
        "streams": streams_n,
        "streams_live_peak": streams_live,
        "gateway_groups": len(groups),
        "replicas": replicas_n,
        "topology": (f"federated tier: {len(groups)} gateway groups "
                     f"(consistent-hash stream->group) over one "
                     f"shared {replicas_n}-replica fleet, loopback"),
        "policy": _SCALE_POLICY,
        "offered_frames": offered,
        "completed": counts["ok"],
        "shed": shed,
        "errors": counts["error"],
        "frames_lost": frames_lost,
        "goodput_fps": round(counts["ok"] / max(elapsed, 1e-9), 1),
        # subset-run headline alias: goodput IS the config's frame rate
        "frames_per_sec_total": round(
            counts["ok"] / max(elapsed, 1e-9), 1),
        "p50_ms": (round(float(np.percentile(latencies, 50)) * 1000, 2)
                   if latencies else None),
        "p99_ms": (round(float(np.percentile(latencies, 99)) * 1000, 2)
                   if latencies else None),
        "broker": {
            "messages": delta("broker.messages"),
            "msgs_per_s": round(
                delta("broker.messages") / max(elapsed, 1e-9), 1),
            "matched_fanout_ratio": round(
                delivered / max(delivered + avoided, 1), 4),
            "fanout_avoided": avoided,
            "match_p50_us": round(snapshot_quantile(
                match_delta, 0.5) * 1e6, 2),
            "match_p99_us": round(snapshot_quantile(
                match_delta, 0.99) * 1e6, 2),
        },
        "trie_vs_linear": {
            "ab_identical": ab_identical,
            "clients": len(subscriptions),
            "messages": len(messages),
            "broker_match_trie_us": round(trie_match_s * 1e6, 3),
            "broker_match_linear_us": round(linear_match_s * 1e6, 3),
            "broker_trie_msgs_per_s": round(trie_msgs_per_s, 1),
            "broker_linear_msgs_per_s": round(linear_msgs_per_s, 1),
            "match_trie_us": round(micro_trie_s * 1e6, 3),
            "match_linear_us": round(micro_linear_s * 1e6, 3),
            "match_speedup": round(
                micro_linear_s / max(micro_trie_s, 1e-12), 2),
        },
    }


def bench_soak(peak):
    """`soak` config: the federated `scale` topology held under
    SUSTAINED stream-churn load (waves of create -> frames -> destroy)
    with a drift ledger -- periodic invariant probes that catch the
    slow leaks a 5-second window never sees.  Probes per wave, at
    quiescence: RSS, open fds, paged-pool block conservation
    (free + cached == capacity on the decode lane), journal size
    after compaction (destroyed streams must leave ZERO entries), and
    telemetry counter reconciliation (per-wave frame conservation,
    admitted+shed == offered streams, share.delta_publishes <=
    share.updates_coalesced).  End-of-window drift assertions: RSS
    slope (mean of last third vs first third) and fd growth bounded.
    `AIKO_SOAK_SECONDS` sets the window (CI runs a bounded slice; the
    full window rides the slow lane); `AIKO_SOAK_LEDGER` names a JSON
    artifact path for the full ledger.  Region-failover correctness
    that only holds for 5-second windows is not robustness -- this
    config is the proof it holds for the long haul."""
    import threading

    from aiko_services_tpu.decode import CheckpointKeeper, reset_keepers
    from aiko_services_tpu.observe.metrics import get_registry
    from aiko_services_tpu.pipeline import create_pipeline
    from aiko_services_tpu.runtime import Process
    from aiko_services_tpu.serve import FederationRouter, Gateway
    from aiko_services_tpu.transport import reset_brokers

    window_s = float(os.environ.get(
        "AIKO_SOAK_SECONDS", "45" if SMOKE else "300"))
    echo_streams = 80 if SMOKE else 200
    frames_per_stream = 2
    decode_streams = 3
    keeper_name = "bench_soak_keeper"
    groups = ("g0", "g1")
    journal_spec = "backend=retained;interval=0.05;search_timeout=0.5"
    policy = "max_inflight=2048;queue=1024"
    registry = get_registry()
    share_before = dict(registry.snapshot()["counters"])

    reset_keepers()
    keeper = CheckpointKeeper(keeper_name)
    processes = []

    def make_process():
        process = Process(transport_kind="loopback")
        processes.append(process)
        return process

    echo_replicas = [create_pipeline(
        make_process(), _scale_definition(f"soak_replica{index}"))
        for index in range(2)]
    decode_replica = create_pipeline(
        make_process(), _chaos_decode_definition(
            "soak_decode", max_new=8, slots=decode_streams + 1,
            keeper=keeper_name))
    gateways = {}
    for group in groups:
        gateways[group] = Gateway(
            make_process(), name=f"soak_{group}", policy=policy,
            federation=f"groups={','.join(groups)};group={group}",
            journal=journal_spec, metrics_interval=3600.0)
        for replica in echo_replicas:
            gateways[group].attach_replica(replica)
    router = FederationRouter(gateways)
    decode_gateway = Gateway(
        make_process(), name="soak_dec", policy="max_inflight=8;queue=32",
        metrics_interval=3600.0,
        checkpoint=f"recovery_rate=4;keeper={keeper_name}")
    decode_gateway.attach_replica(decode_replica)
    for process in processes:
        process.run(in_thread=True)

    import numpy as np
    rng = np.random.default_rng(7)
    page_kb = os.sysconf("SC_PAGE_SIZE") // 1024

    def rss_kb():
        try:
            with open("/proc/self/statm") as handle:
                return int(handle.read().split()[1]) * page_kb
        except (OSError, IndexError, ValueError):
            return None

    def open_fds():
        try:
            return len(os.listdir("/proc/self/fd"))
        except OSError:
            return None

    def wait(predicate, timeout=60.0, what="soak condition"):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if predicate():
                return True
            time.sleep(0.005)
        raise TimeoutError(f"{what} not met within {timeout}s")

    ledger: list = []
    findings: list = []
    streams_total = 0
    frames_total = 0
    wave = 0
    start = time.perf_counter()
    deadline = time.monotonic() + window_s
    while time.monotonic() < deadline:
        wave += 1
        offered = echo_streams * frames_per_stream + decode_streams
        responses = queue.Queue()
        answered = {"ok": 0, "shed": 0, "error": 0}
        # -- the churn wave: echo storm through the federated tier,
        #    a few checkpointed decode streams on the side
        echo_ids = [f"w{wave}s{index}" for index in range(echo_streams)]
        for stream_id in echo_ids:
            router.submit_stream(stream_id, queue_response=responses,
                                 grace_time=600)
        for frame_id in range(frames_per_stream):
            for index, stream_id in enumerate(echo_ids):
                router.submit_frame(stream_id, {"number": index},
                                    frame_id=frame_id)
        decode_ids = [f"w{wave}d{index}"
                      for index in range(decode_streams)]
        for stream_id in decode_ids:
            decode_gateway.submit_stream(
                stream_id, {}, queue_response=responses,
                grace_time=600)
            decode_gateway.submit_frame(
                stream_id,
                {"tokens": rng.integers(1, 300, size=(1, 6))
                 .astype(np.int32)},
                frame_id=0)
        for _ in range(offered):
            try:
                _sid, _fid, _out, status = responses.get(timeout=120)
            except queue.Empty:
                break
            answered[status if status in answered else "error"] += 1
        streams_total += echo_streams + decode_streams
        frames_total += offered
        # -- drain to quiescence: destroy everything, then probe
        for stream_id in echo_ids:
            router.destroy_stream(stream_id)
        for stream_id in decode_ids:
            decode_gateway.post_message("destroy_stream", [stream_id])
        try:
            wait(lambda: not any(gateway.streams for gateway in
                                 gateways.values())
                 and not decode_gateway.streams,
                 what=f"wave {wave} stream teardown")
            wait(lambda: (decode_replica.elements["lm"]
                          .engine_stats() or {}).get("active_slots",
                                                     -1) == 0,
                 what=f"wave {wave} decode slot release")
            for gateway in gateways.values():
                wait(lambda g=gateway: g.journal.entry_count() == 0
                     or g.journal.compact() >= 0
                     and g.journal.entry_count() == 0,
                     timeout=15,
                     what=f"wave {wave} journal drain")
        except TimeoutError as error:
            findings.append(str(error))
        # -- the drift probes
        delivered = answered["ok"] + answered["shed"] + answered["error"]
        if delivered != offered:
            findings.append(
                f"wave {wave}: frame conservation broke -- "
                f"{delivered}/{offered} answered")
        admitted = sum(gateway.telemetry.admitted.value
                       + gateway.telemetry.shed_streams.value
                       for gateway in gateways.values())
        admitted += (decode_gateway.telemetry.admitted.value
                     + decode_gateway.telemetry.shed_streams.value)
        if admitted != streams_total:
            findings.append(
                f"wave {wave}: admission reconciliation broke -- "
                f"admitted+shed {admitted} != offered {streams_total}")
        engine = decode_replica.elements["lm"].engine_stats() or {}
        pool_free = engine.get("free_blocks", 0)
        pool_cached = engine.get("prefix_cached_blocks", 0)
        pool_capacity = engine.get("blocks", 0)
        if pool_free + pool_cached != pool_capacity:
            findings.append(
                f"wave {wave}: paged-pool leak -- free {pool_free} + "
                f"cached {pool_cached} != capacity {pool_capacity}")
        journal_entries = sum(gateway.journal.entry_count()
                              for gateway in gateways.values())
        if journal_entries:
            findings.append(
                f"wave {wave}: journal kept {journal_entries} "
                f"entr(ies) after compaction at quiescence")
        counters = registry.snapshot()["counters"]

        def share_delta(name):
            return (counters.get(name, 0)
                    - share_before.get(name, 0))

        if (share_delta("share.delta_publishes")
                > share_delta("share.updates_coalesced")):
            findings.append(
                f"wave {wave}: share coalescing inverted -- "
                f"{share_delta('share.delta_publishes')} delta "
                f"publishes from "
                f"{share_delta('share.updates_coalesced')} staged "
                f"updates")
        ledger.append({
            "wave": wave,
            "t_s": round(time.perf_counter() - start, 2),
            "rss_kb": rss_kb(),
            "open_fds": open_fds(),
            "pool_free": pool_free,
            "pool_cached": pool_cached,
            "pool_capacity": pool_capacity,
            "journal_entries": journal_entries,
            "answered": delivered,
            "offered": offered,
            "findings_total": len(findings),
        })
    elapsed = time.perf_counter() - start
    # -- end-of-window drift assertions over the whole ledger
    rss_series = [entry["rss_kb"] for entry in ledger
                  if entry["rss_kb"] is not None]
    if len(rss_series) >= 3:
        third = max(len(rss_series) // 3, 1)
        early = sum(rss_series[:third]) / third
        late = sum(rss_series[-third:]) / third
        budget_kb = max(32768.0, early * 0.10)
        if late - early > budget_kb:
            findings.append(
                f"rss drift: {early:.0f} kB -> {late:.0f} kB "
                f"(budget {budget_kb:.0f} kB over the window)")
        rss_drift_kb = round(late - early, 1)
    else:
        rss_drift_kb = None
    fd_series = [entry["open_fds"] for entry in ledger
                 if entry["open_fds"] is not None]
    if len(fd_series) >= 2 and fd_series[-1] > fd_series[0] + 16:
        findings.append(
            f"fd drift: {fd_series[0]} -> {fd_series[-1]} open fds")
    for process in processes:
        try:
            process.terminate()
        except Exception:
            pass
    reset_keepers()
    reset_brokers()
    ledger_path = os.environ.get("AIKO_SOAK_LEDGER")
    if ledger_path:
        try:
            with open(ledger_path, "w") as handle:
                json.dump({"findings": findings, "ledger": ledger},
                          handle, indent=2)
        except OSError as error:
            findings.append(f"ledger write failed: {error}")
    return {
        "window_s": window_s,
        "elapsed_s": round(elapsed, 1),
        "waves": wave,
        "streams_total": streams_total,
        "frames_total": frames_total,
        "drift_ok": not findings,
        "findings": findings,
        "rss_drift_kb": rss_drift_kb,
        "open_fds_first": fd_series[0] if fd_series else None,
        "open_fds_last": fd_series[-1] if fd_series else None,
        "probes": len(ledger),
        # the ledger rides the block (bounded); the full artifact goes
        # to AIKO_SOAK_LEDGER for CI upload
        "ledger": ledger[-40:],
        "ledger_file": ledger_path,
        "topology": (f"federated tier ({len(groups)} journaled "
                     f"gateway groups, 2 echo replicas) + 1 "
                     f"checkpointed decode lane, loopback"),
    }


def bench_tts(peak):
    """Text -> speech through the pipeline element (chars -> mel ->
    Griffin-Lim, ONE jit per frame batch): the last model family's
    on-chip number (reference seat: Coqui TTS on CUDA,
    speech_elements.py:109-146)."""
    from aiko_services_tpu.models.configs import tts_flops_per_example
    from aiko_services_tpu.models.tts import TTSConfig

    phrase = ("the quick brown fox jumps over the lazy dog"
              if not SMOKE else "hello")
    batch = 2 if SMOKE else int(os.environ.get("AIKO_BENCH_TTS_BATCH",
                                               "8"))
    warmup, measure = (2, 4) if SMOKE else (5, 40)
    config = TTSConfig()
    definition = _tts_definition(phrase, batch,
                                 (warmup + measure + 4) * batch)
    fps, p50, drain_pf, outputs = _run_pipeline(
        definition, warmup=warmup, measure=measure, ready_key="audio")
    # REAL speech seconds: the element pads prompts to power-of-two
    # char buckets, so the waveform length covers pad-silence; count
    # only the phrase's own frames (matches the FLOPs denominator)
    seconds = (len(phrase) * config.frames_per_char * config.hop
               / config.sample_rate)
    flops = tts_flops_per_example(config, len(phrase)) * batch
    return {"frames_per_sec_chip": round(fps, 2),
            "telemetry": TELEMETRY,
            **_latency_fields(p50, drain_pf),
            "audio_seconds_per_frame": round(seconds * batch, 2),
            "speech_sec_per_sec": round(fps * batch * seconds, 1),
            "batch": batch,
            "mfu": _mfu(fps * flops, peak)}


def collect_definitions() -> dict:
    """Every pipeline definition the benchmark constructs, keyed by
    config name -- the `aiko lint --bench` / CI lint surface.  Built by
    the SAME builders the bench entry points call, so linting these
    lints exactly what runs (the analyzer's golden-corpus acceptance:
    zero strict-mode findings here)."""
    from aiko_services_tpu.models.configs import (
        DETECTOR_TOY, YOLOV8N_SHAPE)

    asr_batch = 2 if SMOKE else int(
        os.environ.get("AIKO_BENCH_ASR_BATCH", "16"))
    det_batch = 2 if SMOKE else int(
        os.environ.get("AIKO_BENCH_DET_BATCH", "16"))
    det_config = DETECTOR_TOY if SMOKE else YOLOV8N_SHAPE
    det_preset = "toy" if SMOKE else "yolov8n"
    rows = 1 if SMOKE else int(os.environ.get("AIKO_BENCH_ROWS", "16"))
    micro = 1 if SMOKE else int(os.environ.get("AIKO_BENCH_MICRO", "8"))
    max_new = 8 if SMOKE else int(os.environ.get("AIKO_BENCH_NEW", "32"))
    serving_micro = 4 if SMOKE else 16
    multimodal, _, _, _ = _multimodal_setup(
        "bench_multimodal", rows, micro, 16, max_new,
        1.0 if SMOKE else 5.0, 16)
    latency, _, _, _ = _multimodal_setup(
        "bench_latency", 1 if SMOKE else 2, 1, 16, max_new,
        1.0 if SMOKE else 5.0, 16)
    return {
        "text": _text_definition(200 if SMOKE else 2000),
        "asr": _asr_definition(
            asr_batch, 1.0 if SMOKE else 5.0, 8 if SMOKE else 32,
            "whisper_tiny" if SMOKE else "whisper_small", 16),
        "detector": _detector_definition(
            det_batch, det_config.image_size, det_preset, 16),
        "multimodal": multimodal,
        "latency": latency,
        "serving": _serving_definition(
            "bench_serving", det_config.image_size,
            {"telemetry": TELEMETRY, "metrics_interval": 60.0},
            {"preset": det_preset, "micro_batch": serving_micro,
             "dtype": "float32" if SMOKE else "bfloat16"}),
        "autoscale": _serving_definition(
            "bench_autoscale", det_config.image_size,
            {"telemetry": TELEMETRY, "metrics_interval": 60.0,
             "autoscale_policy": _AUTOSCALE_POLICY},
            {"preset": det_preset, "micro_batch": serving_micro,
             "dtype": "float32" if SMOKE else "bfloat16"}),
        "autopilot": _autopilot_definition("bench_autopilot"),
        "chaos": _chaos_definition("bench_chaos"),
        "chaos_decode": _chaos_decode_definition("bench_chaos_decode"),
        "prefix_cache": _prefix_cache_definition("bench_prefix_cache"),
        "scale": _scale_definition("bench_scale"),
        "tts": _tts_definition(
            "hello" if SMOKE else
            "the quick brown fox jumps over the lazy dog",
            2 if SMOKE else 8, 16),
    }


# Hard cap on the FINAL printed line.  The driver records only the last
# ~2000 chars of bench output; round 4's single fat JSON line outgrew
# that window and the headline metric was lost ("parsed": null in
# BENCH_r04.json).  The final line must always fit with margin.
HEADLINE_LINE_CAP = 1200

# one representative scalar per config for the compact summary:
# config name -> (field in that config's dict, short key in summary)
_SUMMARY_FIELDS = (
    ("asr", "mfu", "asr_mfu"),
    ("detector", "mfu", "det_mfu"),
    ("llm", "tokens_per_sec", "llm_tok_s"),
    ("llm", "decode_mfu", "llm_mfu"),
    ("train", "train_mfu", "train_mfu"),
    ("serving", "coalescing_speedup", "serving_speedup"),
    ("serving", "frames_per_sec_total", "serving_fps"),
    ("chunked_prefill", "stall_speedup", "chunk_stall_speedup"),
    ("spec_decode", "accepted_len_mean", "spec_accept_mean"),
    ("spec_decode", "ceiling_speedup", "spec_ceiling_speedup"),
    ("prefix_cache", "hit_rate", "prefix_hit_rate"),
    ("prefix_cache", "ttft_collapse", "prefix_ttft_collapse"),
    ("prefix_cache", "bit_identical", "prefix_bit_identical"),
    ("latency", "p50_ms", "latency_p50_ms"),
    ("autoscale", "time_to_healthy_warm_ms", "tth_warm_ms"),
    ("autoscale", "warm_vs_cold_speedup", "warm_speedup"),
    ("autopilot", "converged", "ap_converged"),
    ("autopilot", "deltas_applied", "ap_deltas"),
    ("chaos", "frames_lost", "chaos_lost"),
    ("chaos", "takeover_ms", "takeover_ms"),
    ("soak", "drift_ok", "soak_drift_ok"),
    ("soak", "waves", "soak_waves"),
    ("scale", "streams", "scale_streams"),
    ("scale", "goodput_fps", "scale_goodput"),
    ("scale", "frames_lost", "scale_lost"),
    ("tts", "mfu", "tts_mfu"),
    ("pipeline_multimodal", "mfu", "headline_mfu"),
    ("pipeline_multimodal", "audio_realtime_factor", "audio_rt"),
)


def compact_headline(detail: dict, cap: int = HEADLINE_LINE_CAP) -> str:
    """The short FINAL output line: headline metric + vs_baseline + a
    one-scalar-per-config summary, guaranteed to parse and to fit in
    `cap` chars (tested in tests/test_bench_output.py).  Full per-config
    detail lives in BENCH_DETAIL.json / the earlier detail line."""
    compact = {key: value for key, value in detail.items()
               if key != "configs"}
    configs = detail.get("configs", {})
    summary = {}
    for config_name, field, short in _SUMMARY_FIELDS:
        value = configs.get(config_name, {}).get(field)
        if value is not None:
            summary[short] = value
    compact["summary"] = summary
    compact["detail_file"] = "BENCH_DETAIL.json"
    # progressive field drops keep the guarantee even if units/summary
    # grow; never drop metric/value/vs_baseline
    for drop in (None, "trace_file", "trace_files", "trace_events",
                 "trace_frames_dropped", "summary",
                 "baseline", "unit", "peak_tflops_assumed",
                 "device_fallback"):
        if drop is not None:
            compact.pop(drop, None)
        line = json.dumps(compact)
        if len(line) <= cap:
            break
    parsed = json.loads(line)  # parse guard: the line IS the record
    assert len(line) <= cap and "vs_baseline" in parsed, (
        f"headline line {len(line)} chars exceeds cap {cap}")
    return line


def _accelerator_failure(timeout: float = 120.0) -> str | None:
    """Probe device init in a SUBPROCESS (a dead device tunnel makes
    jax.devices() hang forever in-process, which would hang the whole
    bench).  None = healthy; otherwise a description of the failure.
    Skippable with AIKO_BENCH_PROBE=0 (costs one extra jax init)."""
    if os.environ.get("AIKO_BENCH_PROBE", "1") == "0":
        return None
    import subprocess
    try:
        probe = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout, capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        return f"device init probe timed out after {timeout:.0f}s"
    if probe.returncode != 0:
        tail = (probe.stderr or "").strip().splitlines()[-1:]
        return (f"device init probe exited {probe.returncode}"
                + (f": {tail[0]}" if tail else ""))
    return None


def main() -> None:
    global SMOKE, _TRACE_PATH, _FAULTS_SEED
    argv = sys.argv[1:]
    usage = ("usage: bench.py [--trace <path>] [--faults <seed>] "
             "[--router <replicas>]")
    if "--trace" in argv:
        index = argv.index("--trace")
        if index + 1 >= len(argv):
            print(usage, file=sys.stderr)
            os._exit(2)
        _TRACE_PATH = argv[index + 1]
    if "--faults" in argv:
        index = argv.index("--faults")
        if index + 1 >= len(argv):
            print(usage, file=sys.stderr)
            os._exit(2)
        _FAULTS_SEED = int(argv[index + 1])
    router_replicas = None
    if "--router" in argv:
        index = argv.index("--router")
        if index + 1 >= len(argv):
            print(usage, file=sys.stderr)
            os._exit(2)
        router_replicas = max(1, int(argv[index + 1]))
    platform = os.environ.get("AIKO_BENCH_PLATFORM")
    device_fallback = None
    if platform:
        import jax
        jax.config.update("jax_platforms", platform)
    else:
        failure = _accelerator_failure()
        if failure is not None:
            # accelerator down: a labeled smoke-scale CPU result beats a
            # hang or a mid-run timeout on full-size models
            device_fallback = f"{failure}; measured smoke-scale on CPU"
            SMOKE = True
            import jax
            jax.config.update("jax_platforms", "cpu")
    import jax

    peak = _peak_flops_per_chip()
    default_configs = ("text,asr,detector,llm,llm_sharded,train,"
                       "longcontext,serving,continuous,chunked_prefill,"
                       "spec_decode,prefix_cache,disagg,autoscale,"
                       "autopilot,chaos,latency,scale,tts,pipeline")
    wanted = os.environ.get("AIKO_BENCH_CONFIGS",
                            default_configs).split(",")
    configs = {}
    if "text" in wanted:
        configs["text"] = _with_control_plane(bench_text)
    if "asr" in wanted:
        configs["asr"] = _with_control_plane(bench_asr, peak)
    if "detector" in wanted:
        configs["detector"] = _with_control_plane(bench_detector, peak)
    if "llm" in wanted:
        configs["llm"] = bench_llm(peak)
    if "llm_sharded" in wanted:
        configs["llm_sharded"] = bench_llm_sharded()
    if "train" in wanted:
        configs["train"] = bench_train(peak)
    if "longcontext" in wanted:
        configs["longcontext"] = bench_longcontext(peak)
    if "serving" in wanted:
        configs["serving"] = _with_control_plane(bench_serving, peak)
    if "continuous" in wanted:
        configs["continuous"] = bench_continuous(peak)
    if "chunked_prefill" in wanted:
        configs["chunked_prefill"] = bench_chunked_prefill(peak)
    if "spec_decode" in wanted:
        configs["spec_decode"] = bench_spec_decode(peak)
    if "prefix_cache" in wanted:
        configs["prefix_cache"] = bench_prefix_cache(peak)
    if "disagg" in wanted:
        configs["disagg"] = _with_control_plane(bench_disagg, peak)
    if router_replicas is not None or "router" in wanted:
        configs["router"] = _with_control_plane(
            bench_router, peak, router_replicas or 2)
    if "autoscale" in wanted:
        configs["autoscale"] = _with_control_plane(bench_autoscale, peak)
    if "autopilot" in wanted:
        configs["autopilot"] = _with_control_plane(bench_autopilot, peak)
    if "chaos" in wanted:
        configs["chaos"] = _with_control_plane(bench_chaos, peak)
    if "latency" in wanted:
        configs["latency"] = _with_control_plane(bench_latency, peak)
    if "scale" in wanted:
        configs["scale"] = _with_control_plane(bench_scale, peak)
    if "soak" in wanted:
        configs["soak"] = _with_control_plane(bench_soak, peak)
    if "tts" in wanted:
        configs["tts"] = _with_control_plane(bench_tts, peak)
    headline_fps, headline_p50, audio_seconds = None, None, None
    headline_rows = 1
    if "pipeline" in wanted:
        meter = _ControlPlaneMeter()
        (configs["pipeline_multimodal"], headline_fps, headline_p50,
         audio_seconds, headline_rows) = bench_multimodal(peak)
        configs["pipeline_multimodal"]["control_plane"] = meter.block()
    metric = "multimodal_pipeline_frames_per_sec"
    unit = ("frames/sec end-to-end (3-stage speech+LM+vision graph, "
            "HBM-resident, 1 chip)")
    if headline_fps is None:
        # subset run (no pipeline config): label the headline with the
        # config it actually came from -- a tokens/sec number must not
        # masquerade as the multimodal frame rate
        first_name, first = next(iter(configs.items()))
        headline_fps = (first.get("frames_per_sec_chip")
                        or first.get("frames_per_sec")
                        or first.get("frames_per_sec_total")
                        or first.get("tokens_per_sec", 0.0))
        headline_p50 = first.get("p50_ms", 0.0) / 1000.0
        metric = f"{first_name}_headline_subset_run"
        unit = (f"headline scalar of the '{first_name}' config "
                f"(SUBSET run -- not the end-to-end pipeline metric)")

    result = {
        "metric": metric,
        "value": round(headline_fps, 2),
        "unit": unit,
        # apples-to-apples baseline: end-to-end audio-realtime factor vs
        # the reference speech stage on a single GPU (whisper-small = 6x
        # realtime, speech_elements.py:186-192 relative-speed table --
        # generous to the reference: its LLM + YOLO stages are free here)
        "vs_baseline": (
            round(headline_fps * headline_rows * audio_seconds
                  / REFERENCE_GPU_SPEECH_REALTIME, 2)
            if audio_seconds is not None
            else round(headline_fps / REFERENCE_FRAMES_PER_SEC, 2)),
        "baseline": (
            "reference whisper-small single-GPU speech stage at 6x "
            "realtime" if audio_seconds is not None
            else "reference multitude broker ceiling 50 frames/sec"),
        "p50_frame_latency_ms": round(headline_p50 * 1000, 2),
        "device": jax.devices()[0].device_kind,
        "peak_tflops_assumed": (round(peak / 1e12, 1) if peak else None),
        "smoke": SMOKE,
        "telemetry": TELEMETRY,
        "configs": configs,
    }
    if device_fallback:
        result["device_fallback"] = device_fallback
    if _FAULTS_SEED is not None:
        result["faults_seed"] = _FAULTS_SEED  # self-describing A/B arm
    if _TRACE_PATH:
        # trace artifacts ship alongside the JSON: one self-describing
        # per-config file each (path published in the config block,
        # `aiko tune` input) plus the combined legacy file with every
        # benched pipeline's spans
        from aiko_services_tpu.observe import chrome_trace_document
        combined_metadata = _write_config_traces(configs, result)
        try:
            with open(_TRACE_PATH, "w") as handle:
                json.dump(chrome_trace_document(
                    _TRACE_EVENTS, metadata=combined_metadata), handle)
            result["trace_file"] = _TRACE_PATH
            result["trace_events"] = len(_TRACE_EVENTS)
            # truncation is explicit: frames evicted from the bounded
            # per-pipeline trace rings (raise with `trace_ring`)
            result["trace_frames_dropped"] = _TRACE_DROPPED
        except OSError as error:
            result["trace_error"] = str(error)
    # full detail: a file (committed evidence) + an earlier output line;
    # the FINAL line is compact so the driver's ~2000-char tail window
    # always contains it whole (round-4 lesson: BENCH_r04 parsed null).
    # Only FULL runs write the file -- a subset run must not clobber the
    # repo's end-to-end evidence record with a partial one
    detail_line = json.dumps(result)
    if set(wanted) >= set(default_configs.split(",")):
        try:
            with open(os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "BENCH_DETAIL.json"), "w") as handle:
                handle.write(detail_line + "\n")
        except OSError:
            pass  # read-only checkout: the detail line still records it
    print(detail_line)
    print(compact_headline(result))
    sys.stdout.flush()
    # hard-exit: skip interpreter teardown -- the tunneled device client's
    # background threads can raise during destructor-time shutdown
    # (observed "FATAL: exception not rethrown" aborts AFTER the result
    # line), and a 134 exit would mark an otherwise-successful bench run
    # as failed
    os._exit(0)


if __name__ == "__main__":
    sys.exit(main())
