# Benchmark: sustained pipeline throughput with a real transformer LM
# element on one chip.
#
# Measures end-to-end frames/sec through the FULL framework path (frame
# generator thread -> pipeline mailbox -> graph execution -> jit-compiled
# transformer forward on device -> response queue), the TPU analogue of the
# reference's multitude load test whose observed ceiling was ~50 frames/sec
# over a localhost MQTT broker (reference: src/aiko_services/examples/
# pipeline/multitude/run_small.sh:9,21 -- "maximum frame rate before
# falling behind").  vs_baseline is the ratio against that 50 Hz ceiling.
#
# Tensors stay HBM-resident end to end (the framework's core design
# property): completion is verified with block_until_ready -- no
# device->host transfer rides the hot path; one transfer at the end checks
# numerics.
#
# Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

from __future__ import annotations

import json
import os
import queue
import sys
import time

REFERENCE_FRAMES_PER_SEC = 50.0  # multitude ceiling, run_small.sh:9

# env-overridable for smoke runs on slow backends
BATCH = int(os.environ.get("AIKO_BENCH_BATCH", 8))
SEQ_LEN = int(os.environ.get("AIKO_BENCH_SEQ", 128))
WARMUP_FRAMES = int(os.environ.get("AIKO_BENCH_WARMUP", 20))
MEASURE_FRAMES = int(os.environ.get("AIKO_BENCH_FRAMES", 200))
N_LAYERS = int(os.environ.get("AIKO_BENCH_LAYERS", 8))
D_MODEL = int(os.environ.get("AIKO_BENCH_DMODEL", 512))


def main() -> None:
    import jax

    # AIKO_BENCH_PLATFORM=cpu: smoke-test on the host platform (needed when
    # another process holds the only TPU; env JAX_PLATFORMS alone is not
    # honored once an accelerator plugin self-registers at import)
    platform = os.environ.get("AIKO_BENCH_PLATFORM")
    if platform:
        jax.config.update("jax_platforms", platform)

    import numpy as np

    from aiko_services_tpu.pipeline import create_pipeline
    from aiko_services_tpu.runtime import Process

    definition = {
        "name": "bench_lm_pipeline",
        "graph": ["(source (lm))"],
        "elements": [
            {"name": "source",
             "output": [{"name": "tokens"}, {"name": "t0"}],
             "parameters": {"data_sources": [[BATCH, SEQ_LEN]],
                            "count": WARMUP_FRAMES + MEASURE_FRAMES + 8},
             "deploy": {"local": {
                 "module": "aiko_services_tpu.elements",
                 "class_name": "TokenSource"}}},
            {"name": "lm", "input": [{"name": "tokens"}],
             "output": [{"name": "logits"}, {"name": "nll"}],
             "parameters": {"vocab_size": 8192, "d_model": D_MODEL,
                            "n_layers": N_LAYERS, "n_heads": 8,
                            "n_kv_heads": 4, "d_ff": 3 * D_MODEL,
                            "dtype": "bfloat16"},
             "deploy": {"local": {
                 "module": "aiko_services_tpu.elements",
                 "class_name": "LMForward"}}},
        ],
    }

    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, definition)
    process.run(in_thread=True)
    responses = queue.Queue()
    pipeline.create_stream("bench", queue_response=responses,
                           grace_time=600)

    latencies = []
    for _ in range(WARMUP_FRAMES):  # covers jit compilation
        _, _, outputs = responses.get(timeout=600)
        jax.block_until_ready(outputs["nll"])
    start = time.perf_counter()
    last_nll = None
    for _ in range(MEASURE_FRAMES):
        _, frame, outputs = responses.get(timeout=600)
        # device completion, not just dispatch -- but NO host transfer
        jax.block_until_ready(outputs["nll"])
        latencies.append(time.time() - outputs["t0"])
        last_nll = outputs["nll"]
    elapsed = time.perf_counter() - start
    nll_host = np.asarray(last_nll)  # single D2H at the end: numerics check
    pipeline.destroy_stream("bench")
    process.terminate()
    assert np.isfinite(nll_host).all(), f"non-finite NLL {nll_host}"

    frames_per_sec = MEASURE_FRAMES / elapsed
    result = {
        "metric": "lm_pipeline_frames_per_sec",
        "value": round(frames_per_sec, 2),
        "unit": (f"frames/sec (batch={BATCH} seq={SEQ_LEN} "
                 f"d{D_MODEL}x{N_LAYERS}L transformer fwd, HBM-resident)"),
        "vs_baseline": round(frames_per_sec / REFERENCE_FRAMES_PER_SEC, 2),
        "p50_frame_latency_ms": round(
            float(np.percentile(latencies, 50) * 1000), 2),
        "tokens_per_sec": round(frames_per_sec * BATCH * SEQ_LEN, 0),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
