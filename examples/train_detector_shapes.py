"""Train the detector to DETECT: synthetic colored squares -> class + box.

Functional-correctness proof for the vision seat (reference parity: the
reference detects because it loads pretrained ultralytics YOLOv8,
yolo.py:51-54; no published checkpoints exist in this image, so
correctness is established by TRAINING to it): each image carries one
axis-aligned colored square on a noisy background; the model must
return exactly one valid detection with the right class and IoU >= 0.7
on HELD-OUT images.

Writes tests/assets/detector_shapes.safetensors, consumed by the
end-to-end pipeline test (tests/test_detector_correctness.py).

Run: python examples/train_detector_shapes.py   (~2-3 min on CPU)
"""
from __future__ import annotations

import pathlib
import sys

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

IMAGE_SIZE = 64
# class -> RGB color of the square
COLORS = np.asarray([
    [0.9, 0.1, 0.1],   # 0: red
    [0.1, 0.9, 0.1],   # 1: green
    [0.1, 0.2, 0.9],   # 2: blue
    [0.9, 0.8, 0.1],   # 3: yellow
], np.float32)


def shape_batch(rng, count: int):
    """Images (B, 3, S, S) with one square each + {"box", "class"}."""
    images = (rng.uniform(0.0, 0.25, (count, 3, IMAGE_SIZE, IMAGE_SIZE))
              .astype(np.float32))
    boxes = np.zeros((count, 4), np.float32)
    classes = rng.integers(0, len(COLORS), count).astype(np.int32)
    for index in range(count):
        side = int(rng.integers(12, 28))
        x0 = int(rng.integers(2, IMAGE_SIZE - side - 2))
        y0 = int(rng.integers(2, IMAGE_SIZE - side - 2))
        color = COLORS[classes[index]] * float(rng.uniform(0.8, 1.0))
        images[index, :, y0:y0 + side, x0:x0 + side] = color[:, None, None]
        boxes[index] = (x0, y0, x0 + side, y0 + side)
    return images, {"box": boxes, "class": classes}


def _iou(a, b) -> float:
    lt = np.maximum(a[:2], b[:2])
    rb = np.minimum(a[2:], b[2:])
    wh = np.maximum(rb - lt, 0.0)
    inter = wh[0] * wh[1]
    union = ((a[2] - a[0]) * (a[3] - a[1])
             + (b[2] - b[0]) * (b[3] - b[1]) - inter)
    return float(inter / max(union, 1e-9))


def main() -> int:
    import jax
    import optax

    from aiko_services_tpu.models import (
        DetectorConfig, detect, init_detector_params,
        make_detector_train_step, save_pytree)

    config = DetectorConfig(
        n_classes=len(COLORS), base_channels=8, image_size=IMAGE_SIZE,
        max_detections=8, score_threshold=0.5, dtype="float32")
    params = init_detector_params(config, jax.random.PRNGKey(0))
    optimizer = optax.adamw(
        optax.cosine_decay_schedule(1e-3, 6000, alpha=0.05))
    opt_state = optimizer.init(params)
    train_step = make_detector_train_step(config, optimizer)

    rng = np.random.default_rng(11)
    heldout_images, heldout_targets = shape_batch(
        np.random.default_rng(5678), 24)

    def heldout_correct() -> tuple:
        result = jax.device_get(detect(params, config, heldout_images))
        good = 0
        for index in range(len(heldout_images)):
            valid = result["valid"][index]
            if valid.sum() != 1:
                continue
            slot = int(np.argmax(valid))
            if int(result["classes"][index][slot]) != int(
                    heldout_targets["class"][index]):
                continue
            if _iou(result["boxes"][index][slot],
                    heldout_targets["box"][index]) < 0.7:
                continue
            good += 1
        return good, len(heldout_images)

    loss = float("nan")
    streak = 0
    for step in range(1, 6001):
        images, targets = shape_batch(rng, 32)
        params, opt_state, loss = train_step(params, opt_state, images,
                                             targets)
        if step % 100 == 0:
            good, total = heldout_correct()
            print(f"step {step}: loss {float(loss):.4f} "
                  f"heldout {good}/{total}", flush=True)
            # demand a STREAK of perfect held-out checks: a single
            # lucky eval is not a robust checkpoint
            streak = streak + 1 if good == total else 0
            if streak >= 3:
                break
    good, total = heldout_correct()
    if good != total:
        print(f"FAILED: held-out {good}/{total}")
        return 1

    asset = (pathlib.Path(__file__).resolve().parent.parent
             / "tests" / "assets" / "detector_shapes.safetensors")
    asset.parent.mkdir(parents=True, exist_ok=True)
    save_pytree(asset, params, metadata={
        "config": {
            "n_classes": config.n_classes,
            "base_channels": config.base_channels,
            "image_size": config.image_size,
            "max_detections": config.max_detections,
            "score_threshold": config.score_threshold,
            "dtype": config.dtype},
        "colors": COLORS.tolist()})
    print(f"saved {asset} ({asset.stat().st_size / 1024:.0f} KiB)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
