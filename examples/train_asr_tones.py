"""Train the tiny ASR to TRANSCRIBE: synthetic tone -> word labels.

Functional-correctness proof for the speech seat (reference parity:
the reference gets transcription from pretrained WhisperX,
speech_elements.py:229-262; no published checkpoints exist in this
image, so correctness is established by TRAINING to it): four tone
classes map to four words; the model must transcribe HELD-OUT tones
(unseen phase/amplitude draws, plus the clean nominal tone) exactly.

Writes tests/assets/asr_tones.safetensors, consumed by the end-to-end
pipeline test (tests/test_asr_correctness.py): audio in -> correct
text out through SpeechToText -> TokensToText.

Run: python examples/train_asr_tones.py   (~1-2 min on CPU)
"""
from __future__ import annotations

import pathlib
import sys

import numpy as np

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

SAMPLE_RATE = 16000
SECONDS = 0.4
# the byte-level toy vocabulary (elements/ml.py): 0=pad 1=sot 2=eot,
# 3..258 = bytes
BYTE_OFFSET = 3
LABELS = {440.0: "alpha", 523.25: "bravo", 659.25: "charlie",
          783.99: "delta"}
TOKEN_WIDTH = 10  # sot + longest word (7) + eot, eot-padded


def encode_label(text: str) -> list[int]:
    data = text.encode("utf-8")
    tokens = [1] + [BYTE_OFFSET + byte for byte in data] + [2]
    return tokens + [2] * (TOKEN_WIDTH - len(tokens))


def tone_batch(rng, per_class: int):
    """Jittered training tones: random phase, amplitude, mild noise,
    +-0.5% frequency wobble."""
    samples = int(SECONDS * SAMPLE_RATE)
    t = np.arange(samples) / SAMPLE_RATE
    audio, tokens = [], []
    for frequency, label in LABELS.items():
        for _ in range(per_class):
            freq = frequency * (1.0 + rng.uniform(-0.005, 0.005))
            phase = rng.uniform(0, 2 * np.pi)
            amplitude = rng.uniform(0.4, 1.1)
            wave = amplitude * np.sin(2 * np.pi * freq * t + phase)
            # noise level spans CLEAN to noisy: a noiseless tone's
            # off-tone mel bins sit at the log floor, a different
            # feature regime than any fixed noise floor -- the clean
            # nominal tone (the pipeline test input) must be in-dist
            wave += rng.normal(0, rng.uniform(0.0, 0.02), samples)
            audio.append(wave.astype(np.float32))
            tokens.append(encode_label(label))
    return np.stack(audio), np.asarray(tokens, np.int32)


def main() -> int:
    import jax
    import optax

    from aiko_services_tpu.models import (
        AsrConfig, init_asr_params, make_asr_train_step, save_pytree,
        transcribe_audio)

    config = AsrConfig(
        n_mels=80, d_model=64, enc_layers=2, dec_layers=2, n_heads=4,
        vocab_size=259, max_frames=24, max_text_len=16, dtype="float32")
    params = init_asr_params(config, jax.random.PRNGKey(0))
    optimizer = optax.adamw(3e-4)
    opt_state = optimizer.init(params)
    train_step = make_asr_train_step(config, optimizer)

    from aiko_services_tpu.ops import log_mel_spectrogram
    mel_fn = jax.jit(
        lambda audio: log_mel_spectrogram(audio, n_mels=config.n_mels))

    rng = np.random.default_rng(7)
    heldout_rng = np.random.default_rng(1234)
    heldout_audio, heldout_tokens = tone_batch(heldout_rng, per_class=4)
    # plus the clean nominal tone per class (what the pipeline test uses)
    samples = int(SECONDS * SAMPLE_RATE)
    t = np.arange(samples) / SAMPLE_RATE
    clean = np.stack([
        np.sin(2 * np.pi * freq * t).astype(np.float32)
        for freq in LABELS])
    clean_tokens = np.asarray(
        [encode_label(label) for label in LABELS.values()], np.int32)
    heldout_audio = np.concatenate([heldout_audio, clean])
    heldout_tokens = np.concatenate([heldout_tokens, clean_tokens])

    def heldout_exact() -> bool:
        out = np.asarray(transcribe_audio(
            params, config, heldout_audio, max_tokens=TOKEN_WIDTH - 1))
        return bool(np.array_equal(out, heldout_tokens[:, 1:]))

    loss = float("nan")
    for step in range(1, 2001):
        audio, tokens = tone_batch(rng, per_class=8)
        mel = mel_fn(audio)
        params, opt_state, loss = train_step(params, opt_state, mel,
                                             tokens)
        if step % 50 == 0:
            exact = heldout_exact()
            print(f"step {step}: loss {float(loss):.4f} "
                  f"heldout_exact={exact}", flush=True)
            if exact and float(loss) < 0.01:
                break
    if not heldout_exact():
        print("FAILED: held-out tones not transcribed exactly")
        return 1

    asset = (pathlib.Path(__file__).resolve().parent.parent
             / "tests" / "assets" / "asr_tones.safetensors")
    asset.parent.mkdir(parents=True, exist_ok=True)
    save_pytree(asset, params, metadata={
        "config": {
            "n_mels": config.n_mels, "d_model": config.d_model,
            "enc_layers": config.enc_layers,
            "dec_layers": config.dec_layers, "n_heads": config.n_heads,
            "vocab_size": config.vocab_size,
            "max_frames": config.max_frames,
            "max_text_len": config.max_text_len, "dtype": config.dtype},
        "labels": {str(freq): label for freq, label in LABELS.items()},
        "seconds": SECONDS})
    print(f"saved {asset} ({asset.stat().st_size / 1024:.0f} KiB)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
