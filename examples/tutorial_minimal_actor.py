# Tutorial: the minimal actor.
#
# The smallest end-to-end aiko_services_tpu program -- one CUSTOM
# pipeline element in a two-element graph, one stream, one frame,
# one response.  No accelerator, no external broker, no model weights:
# everything runs in-process on the loopback transport.
#
#   python examples/tutorial_minimal_actor.py
#
# Concepts, in the order they appear:
#
#   1. ELEMENT  -- a PipelineElement subclass.  `process_frame(stream,
#      **inputs)` receives the frame's named inputs and returns
#      (StreamEvent.OKAY, {named outputs}).  Elements are ACTORS: all
#      calls arrive through one mailbox, so no locking is ever needed.
#   2. DEFINITION -- the JSON-shaped dict naming the graph topology and
#      each element's ports, parameters, and deploy target.  The same
#      dict could live in a .json file (`aiko pipeline <file>`), and
#      `aiko lint` statically checks it either way.
#   3. STREAM / FRAME -- a stream is a session with per-stream
#      parameters; each frame carries a dict of named values through
#      the graph.  `queue_response` delivers the leaf outputs back.
#
# Where to go next: parameters + `get_parameter` precedence (stream >
# element > pipeline) below; `ComputeElement` for jitted device
# kernels; `micro_batch` / `continuous` for batching (README
# "Continuous batching"); examples/pipeline_*.json for real graphs.

from __future__ import annotations

import pathlib
import queue
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from aiko_services_tpu.pipeline import (PipelineElement, StreamEvent,
                                        create_pipeline)
from aiko_services_tpu.runtime import Process


class Shout(PipelineElement):
    """text in -> the same text, LOUDER.  The whole element contract is
    this one method; start_stream/stop_stream/frame generators are
    opt-in extras."""

    def process_frame(self, stream, text):
        suffix = str(self.get_parameter("suffix", "!", stream))
        texts = [text] if isinstance(text, str) else list(text)
        shouted = [str(part).upper() + suffix for part in texts]
        return StreamEvent.OKAY, {"text": shouted}


DEFINITION = {
    "name": "tutorial",
    # one graph expression: source feeds shout
    "graph": ["(source (shout))"],
    "elements": [
        {"name": "source",
         "output": [{"name": "text", "type": "str"}],
         # TextSource emits one frame per data_sources item
         "parameters": {"data_sources": ["hello, actor"]},
         "deploy": {"local": {"module": "aiko_services_tpu.elements",
                              "class_name": "TextSource"}}},
        {"name": "shout",
         "input": [{"name": "text", "type": "str"}],
         "output": [{"name": "text", "type": "str"}],
         # module "__main__" resolves to THIS file when run directly;
         # real deployments name an importable module instead
         "deploy": {"local": {"module": __name__,
                              "class_name": "Shout"}}},
    ],
}


def main() -> list:
    process = Process(transport_kind="loopback")
    pipeline = create_pipeline(process, DEFINITION)
    process.run(in_thread=True)

    responses = queue.Queue()
    pipeline.create_stream("tutorial_stream", queue_response=responses,
                           parameters={"suffix": "!!"})
    # the source element generates the frame; we just collect the leaf
    stream, frame, outputs = responses.get(timeout=60)
    print(f"stream {stream.stream_id!r} frame {frame.frame_id}: "
          f"{outputs['text']}")

    process.terminate()
    return outputs["text"]


if __name__ == "__main__":
    main()
