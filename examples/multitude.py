# Multitude load test: chained remote pipelines, the formalized version of
# the reference's shell-script load test (reference: src/aiko_services/
# examples/pipeline/multitude/run_small.sh -- 3 chained remote PE_Add
# pipelines driven by mosquitto_pub, observed ceiling ~50 frames/sec;
# run_large.sh scales to 10).
#
#   python examples/multitude.py --pipelines 3 --frames 200
#
# Builds N pipelines where pipeline i's "add" element is REMOTE, served by
# pipeline i+1 (the last one is fully local), drives frames through the
# chain, and reports sustained frames/sec -- directly comparable to the
# reference's 50 Hz number, on the loopback broker (or MQTT via
# AIKO_MQTT_HOST).

from __future__ import annotations

import argparse
import queue
import time


def chained_definition(index: int, count: int) -> dict:
    """Each pipeline adds 1 locally, then (except the last) forwards the
    frame to the next pipeline in the chain as a remote element -- the
    reference multitude topology (run_small.sh:53-61)."""
    elements = [
        {"name": "add",
         "input": [{"name": "number"}],
         "output": [{"name": "number"}],
         "parameters": {"constant": 1},
         "deploy": {"local": {"module": "aiko_services_tpu.elements",
                              "class_name": "PE_Add"}}},
    ]
    if index == count - 1:
        graph = ["(add)"]
    else:
        graph = ["(add (next))"]
        elements.append(
            {"name": "next",
             "input": [{"name": "number"}],
             "output": [{"name": "number"}],
             "deploy": {"remote": {"service_filter": {
                 "name": f"multitude_{index + 1}"}}}})
    return {"name": f"multitude_{index}", "graph": graph,
            "elements": elements}


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--pipelines", type=int, default=3)
    parser.add_argument("--frames", type=int, default=200)
    parser.add_argument("--transport", default="loopback")
    arguments = parser.parse_args()

    from aiko_services_tpu.pipeline import create_pipeline
    from aiko_services_tpu.runtime import Process, Registrar

    registrar_process = Process(transport_kind=arguments.transport)
    Registrar(registrar_process, search_timeout=0.1)
    registrar_process.run(in_thread=True)

    processes, pipelines = [], []
    for index in reversed(range(arguments.pipelines)):
        process = Process(transport_kind=arguments.transport)
        pipelines.insert(0, create_pipeline(
            process, chained_definition(index, arguments.pipelines)))
        process.run(in_thread=True)
        processes.append(process)

    head = pipelines[0]
    deadline = time.time() + 30
    while time.time() < deadline and not head.ready:
        time.sleep(0.05)
    if not head.ready:
        raise SystemExit("chain never became ready")

    responses = queue.Queue()
    head.create_stream("load", queue_response=responses, grace_time=300)
    # warmup
    for index in range(10):
        head.process_frame({"stream_id": "load"}, {"number": index})
    for _ in range(10):
        responses.get(timeout=30)

    start = time.perf_counter()
    in_flight = 0
    completed = 0
    sent = 0
    while completed < arguments.frames:
        while in_flight < 32 and sent < arguments.frames:
            head.process_frame({"stream_id": "load"}, {"number": sent})
            sent += 1
            in_flight += 1
        _, _, outputs = responses.get(timeout=30)
        # each of the N chained pipelines added 1
        assert int(outputs["number"]) >= arguments.pipelines
        completed += 1
        in_flight -= 1
    elapsed = time.perf_counter() - start

    rate = arguments.frames / elapsed
    print(f"multitude: {arguments.pipelines} chained pipelines, "
          f"{arguments.frames} frames, {rate:.1f} frames/sec "
          f"(reference ceiling: ~50 frames/sec, run_small.sh:9)")

    for process in processes + [registrar_process]:
        process.terminate()


if __name__ == "__main__":
    main()
